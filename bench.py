"""Benchmark entry point — prints ONE JSON line for the driver, always.

Headline metric (BASELINE.json north star): GraphSAGE topology-model
training throughput in samples(edges)/sec/chip, steady-state (compile
excluded). Extras carry the second tracked number — scheduler
parent-selection latency through the TPU-backed ML scorer (<1 ms
colocated target), now measured end-to-end through the micro-batcher
under 8-thread concurrent load — plus MLP training stats and pipeline
diagnostics.

Round-4 architecture (the round-3 failure was a one-shot TPU probe that
hit a tunnel outage and committed the whole run to CPU):

  orchestrator (this process)
  ├── CPU insurance worker  (subprocess, small shapes, starts at t=0)
  ├── TPU probe loop        (retry with backoff THROUGHOUT the budget)
  └── TPU worker            (subprocess, launched when a probe succeeds,
                             relaunched after re-probe if it dies early)

Both workers run the same staged benchmark (``--worker`` mode below) and
persist their full result JSON atomically after EVERY progress update,
so a mid-run tunnel drop still leaves an on-chip artifact on disk
(BENCH_STATE_DIR, default artifacts/bench_state/). The orchestrator
merges continuously: the headline is the TPU worker's number the moment
it exists, the CPU number only if the chip never materializes. The CPU
worker is terminated once the TPU worker publishes a nonzero headline
(its job — insurance against a dead tunnel — is done, and it would
otherwise contend for host cores the TPU input pipeline needs).

Un-killability contract (round-1 failure: silent rc=124): a watchdog
thread in the orchestrator force-emits the merged best-so-far before the
driver's kill horizon; workers carry their own watchdogs (os._exit works
even when the main thread is blocked inside a hung device call) and
budget themselves to finish before the orchestrator's margin.

``vs_baseline`` is measured/target against the self-established target
(the reference publishes no numbers and its training path is a stub; see
BASELINE.md): 100k samples/sec/chip for GraphSAGE training.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

TARGET_GNN_SAMPLES_PER_SEC_PER_CHIP = 100_000.0
TARGET_P50_MS = 1.0
# Round-5 latency budget (verdict item 6), extended at round 6 from 8 to
# 32 scheduler threads: colocated parent-selection p99 must stay under
# 2 ms on the CPU device at BOTH rungs — the lane-sharded micro-batcher
# owes a tail bound under real announce concurrency (the reference
# scheduler is per-stream concurrent, service_v2.go:88), not just at the
# 8-thread comfort point. The 128-thread rung is bounded by admission
# control: p99 within 2× the 32-thread row, shed rate reported.
COLOCATED_P99_TARGET_MS = 2.0
COLOCATED_P99_TARGET_THREADS = 32
# Lane-sharded serving config for the ladder: 2 independent pipelined
# lanes with a 32-deep admission cap each, load-aware activation
# (lane_grow_depth defaults to max_rows/16 = 32 requests — one full
# 512-row dispatch). Measured shape on the 2-core dev box: 8/32 threads
# stay on ONE active lane (full coalescing, zero sheds — identical to
# the pre-lane pipeline), 128 threads activate the second lane and the
# caps bound every lane's backlog to one large dispatch of waiting work,
# shedding the rest to the (counted) rule fallback — p99 within 2× the
# 32-thread row versus ~8× unbounded. 4 lanes measured worse here
# (fragmented coalescing + XLA CPU contention); raise on bigger hosts.
COLOCATED_LANES = 2
COLOCATED_LANE_DEPTH = 32

# Total wall budget. The driver's observed kill horizon is >240 s; leave
# margin so the watchdog always wins the race against SIGKILL.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "200"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT_S", "25"))
STATE_DIR = os.environ.get(
    "BENCH_STATE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "artifacts", "bench_state"))

_t0 = time.perf_counter()


def elapsed() -> float:
    return time.perf_counter() - _t0


def remaining() -> float:
    return BUDGET_S - elapsed()


class BenchState:
    """The result dict + thread-safe mutation + atomic disk persistence.

    Every mutation holds a reentrant lock so a watchdog can never
    serialize a dict mid-mutation; ``flush`` writes tmp+rename so a
    reader (the orchestrator) never sees a torn file.
    """

    def __init__(self, out_path: str | None = None):
        self.lock = threading.RLock()
        self.out_path = out_path
        self.emitted = False
        self.result = {
            "metric": "graphsage_train_samples_per_sec_per_chip",
            "value": 0,
            "unit": "samples/sec/chip",
            "vs_baseline": 0.0,
            "extras": {"stages_completed": [], "platform": "unknown"},
        }

    def record(self, **extras) -> None:
        with self.lock:
            self.result["extras"].update(extras)
        self.flush()

    def stamp(self, name: str) -> None:
        self.record(**{f"t_{name}": round(elapsed(), 1)})

    def stage_done(self, name: str) -> None:
        with self.lock:
            self.result["extras"]["stages_completed"].append(name)
        self.stamp(name)

    def set_headline(self, value: float) -> None:
        with self.lock:
            self.result["value"] = int(value)
            self.result["vs_baseline"] = round(
                value / TARGET_GNN_SAMPLES_PER_SEC_PER_CHIP, 3)
        self.flush()

    def flush(self) -> None:
        if not self.out_path:
            return
        with self.lock:
            blob = json.dumps(self.result)
        tmp = self.out_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, self.out_path)
        except OSError:
            pass

    def emit(self) -> None:
        with self.lock:
            if self.emitted:
                return
            self.result["extras"]["wall_seconds"] = round(elapsed(), 1)
            line = json.dumps(self.result)
            self.emitted = True
        self.flush()
        print(line, flush=True)


# --------------------------------------------------------------------------
# Worker: runs the actual staged benchmark on one platform.
#
# Stages live in ONE registry (STAGES, populated by @stage below), not a
# hand-maintained if/elif chain: the runner iterates the registry in
# declaration order, applies each stage's budget guard, and wraps
# optional stages' failures into <name>_error extras — so a new stage
# cannot be silently dropped from the ladder, and `bench.py <stage>`
# can run any single stage by name.
# --------------------------------------------------------------------------

STAGES: list = []


def _persist_json(dest: str, payload: dict) -> None:
    """Atomic best-effort stage-record write (tmp + rename) — the one
    copy of the idiom the green-run persists share."""
    tmp = dest + ".tmp"
    try:
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, dest)
    except OSError:
        pass


class _Stage:
    __slots__ = ("name", "min_left", "required", "needs_device", "fn")

    def __init__(self, name, min_left, required, needs_device, fn):
        self.name = name
        self.min_left = min_left
        self.required = required
        self.needs_device = needs_device
        self.fn = fn


def stage(name: str, *, min_left: float = 0.0, required: bool = False,
          needs_device: bool = False):
    """Register a bench stage. ``min_left`` skips the stage when less
    wall budget remains; ``required`` propagates its failures (headline
    stages) instead of recording <name>_error; ``needs_device`` makes
    single-stage runs execute the init stage first."""

    def deco(fn):
        STAGES.append(_Stage(name, min_left, required, needs_device, fn))
        return fn

    return deco


@stage("init", required=True)
def stage_init(state: BenchState, ctx: dict) -> None:
    platform = ctx["platform"]
    if platform != "tpu":
        # Must happen before ANY backend use; the env var alone is
        # overridden by this machine's sitecustomize.
        import jax

        jax.config.update("jax_platforms", "cpu")

    from dragonfly2_tpu.utils.compilecache import enable_compilation_cache

    state.record(compile_cache_dir=enable_compilation_cache())

    import jax

    from dragonfly2_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh()
    ctx["mesh"] = mesh
    state.record(platform=jax.devices()[0].platform, n_devices=mesh.n_data)
    state.stage_done("init")


@stage("scorer", required=True, needs_device=True)
def stage_scorer(state: BenchState, ctx: dict) -> None:
    left = ctx["left"]
    # Parent-selection latency FIRST — it is weight-independent
    # (a synthetically initialized MLP exercises the same compiled
    # dispatch path a trained one would), so the <1 ms target gets
    # validated before the GNN stage can starve it. Two measurements:
    #   (a) single-threaded ParentScorer loop (the round-3 number), and
    #   (b) the COLOCATED number the target is actually about — 8
    #       scheduler threads through the MicroBatcher, end-to-end
    #       (round-3 verdict item 5).
    # Both are decomposed against the dispatch floor (a blocking no-op
    # jit round trip: the tunneled axon TPU pays a network RTT per call
    # — observed ~68 ms — so raw and floor-corrected are published side
    # by side, clearly labeled).
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.inference import ParentScorer
    from dragonfly2_tpu.inference.loadgen import measure_colocated
    from dragonfly2_tpu.models.mlp import MLPBandwidthPredictor, Normalizer
    from dragonfly2_tpu.scheduler.evaluator.scoring import FEATURE_DIM

    scorer_budget = max(min(left() * 0.2, 30.0), 4.0)
    scorer_t0 = time.perf_counter()

    mlp_model = MLPBandwidthPredictor()
    mlp_params = mlp_model.init(jax.random.key(0),
                                jnp.zeros((1, FEATURE_DIM)))
    # max_batch=512: the batcher drains up to the largest warm bucket,
    # so at 128 threads × 16 rows a dispatch can coalesce 32 requests —
    # the r05 ladder pinned at 8 because 128 rows was the ceiling. All
    # buckets compile here, before timing: the ladder must be cache hits
    # only.
    scorer = ParentScorer(mlp_model, mlp_params,
                          Normalizer.identity(FEATURE_DIM),
                          Normalizer.identity(1), max_batch=512)

    noop = jax.jit(lambda x: x + 1)
    x0 = jnp.zeros(8)
    noop(x0).block_until_ready()
    floor = []
    for _ in range(15):
        t = time.perf_counter()
        noop(x0).block_until_ready()
        floor.append((time.perf_counter() - t) * 1e3)
    floor_p50 = sorted(floor)[len(floor) // 2]
    state.record(dispatch_floor_p50_ms=round(floor_p50, 4))

    # (a) single-threaded loop, adaptive iteration count.
    probe = scorer.benchmark(batch=16, iters=10)
    solo_budget = (scorer_budget - (time.perf_counter() - scorer_t0)) * 0.4
    iters = int(max(20, min(300,
                            solo_budget * 1e3 / max(probe["p50_ms"], 1e-3))))
    latency = scorer.benchmark(batch=16, iters=iters)
    state.record(
        parent_select_p50_ms=round(latency["p50_ms"], 4),
        parent_select_p99_ms=round(latency["p99_ms"], 4),
        parent_select_iters=iters,
        parent_select_model_ms=round(
            max(latency["p50_ms"] - floor_p50, 0.0), 4),
        parent_select_vs_1ms_target=round(
            TARGET_P50_MS / max(latency["p50_ms"], 1e-9), 3),
    )

    # (b) colocated: concurrent scheduler threads → lane-sharded
    # MicroBatcher → one padded dispatch per lane in-flight window.
    # parent_select_colocated_* fields are the deliverable named by the
    # round-3 verdict; the 8/32/128-thread ladder is round 5's (verdict
    # item 6); round 6 shards the batcher into lanes with bounded
    # admission and moves the stated p99 < 2 ms target out to 32
    # threads, with the 128-thread rung bounded (p99 ≤ 2× the 32-thread
    # row) by shedding — the shed rate is reported, never dropped.
    colo_secs = max(min((scorer_budget
                         - (time.perf_counter() - scorer_t0)) / 3, 4.0), 1.0)
    load_ladder = {}
    for n_threads in (8, 32, 128):
        colo = measure_colocated(scorer, threads=n_threads,
                                 rows_per_request=16,
                                 duration_s=colo_secs,
                                 dispatch_floor_ms=floor_p50,
                                 adaptive_wait_s=0.0005,
                                 lanes=COLOCATED_LANES,
                                 queue_depth=COLOCATED_LANE_DEPTH)
        load_ladder[n_threads] = colo
        if n_threads == 8:
            state.record(
                parent_select_colocated_p50_ms=colo["p50_ms"],
                parent_select_colocated_p95_ms=colo["p95_ms"],
                parent_select_colocated_p99_ms=colo["p99_ms"],
                parent_select_colocated_p50_floor_corrected_ms=colo[
                    "p50_floor_corrected_ms"],
                parent_select_colocated_requests_per_sec=colo[
                    "requests_per_sec"],
                parent_select_colocated_coalesce_factor=colo[
                    "coalesce_factor"],
                parent_select_colocated_threads=colo["threads"],
                parent_select_colocated_sheds=colo["sheds"],
            )
        elif n_threads == COLOCATED_P99_TARGET_THREADS:
            state.record(
                parent_select_colocated32_p99_ms=colo["p99_ms"],
                parent_select_colocated32_shed_rate=colo["shed_rate"],
                parent_select_colocated_p99_target_ms=COLOCATED_P99_TARGET_MS,
                parent_select_colocated_p99_target_threads=(
                    COLOCATED_P99_TARGET_THREADS),
                parent_select_colocated_p99_vs_target=round(
                    COLOCATED_P99_TARGET_MS / max(colo["p99_ms"], 1e-9), 3),
            )
    p99_32 = load_ladder[32]["p99_ms"]
    state.record(
        parent_select_colocated_lanes=COLOCATED_LANES,
        parent_select_colocated_lane_depth=COLOCATED_LANE_DEPTH,
        parent_select_colocated128_p99_over_32=round(
            load_ladder[128]["p99_ms"] / max(p99_32, 1e-9), 3),
        parent_select_colocated128_shed_rate=load_ladder[128]["shed_rate"],
    )
    state.record(parent_select_colocated_load_ladder={
        str(k): {f: v[f] for f in ("p50_ms", "p95_ms", "p99_ms",
                                   "requests_per_sec", "coalesce_factor",
                                   "requests", "inflight_depth_avg",
                                   "overlap_ratio", "adaptive_opens",
                                   "max_queue_depth", "lanes",
                                   "active_lanes", "lane_activations",
                                   "queue_depth_cap", "sheds", "shed_rate",
                                   "per_lane", "bucket_hits")}
        for k, v in load_ladder.items()})
    state.stage_done("scorer")


@stage("gnn", required=True, needs_device=True)
def stage_gnn(state: BenchState, ctx: dict) -> None:
    """Headline: GraphSAGE on a probe graph. The step loop gets the
    remaining budget minus reserves for eval + emit, and publishes
    throughput incrementally so a watchdog fire always has the latest
    steady-state rate. CPU insurance shrinks the problem so every stage
    COMPLETES — a small honest number beats a kill mid-compile."""
    left = ctx["left"]
    platform = ctx["platform"]
    mesh = ctx["mesh"]

    from dragonfly2_tpu.data import SyntheticCluster
    from dragonfly2_tpu.train import GNNTrainConfig, train_gnn

    if platform == "tpu":
        # (8192, 16) won the round-4 on-chip grid (artifacts/
        # tune_gnn_r4.json: 351k vs 275k at k=8 in matched windows) —
        # deeper scan amortizes the tunnel dispatch further.
        n_edges, batch, steps_per_call = 2_000_000, 8192, 16
    else:
        n_edges, batch, steps_per_call = 200_000, 2048, 1
    cluster = ctx["cluster"] = SyntheticCluster(n_hosts=2000, seed=0)
    graph = cluster.probe_graph(n_edges)
    state.stamp("graph_built")

    def on_progress(steps: int, rate: float) -> None:
        state.set_headline(rate / mesh.n_data)
        state.record(gnn_steps=steps)

    def on_compile(seconds: float) -> None:
        state.record(gnn_compile_seconds=round(seconds, 1))
        state.stamp("gnn_compile_done")

    eval_reserve = max(min(left() * 0.2, 30.0), 5.0)
    emit_reserve = 10.0
    compile_reserve = 30.0  # uncached train-step compile; ~0 on cache hit
    gnn_budget = max(left() - eval_reserve - emit_reserve - compile_reserve,
                     5.0)
    state.record(gnn_step_seconds_budget=round(gnn_budget, 1))
    gnn = train_gnn(
        graph,
        # steps_per_call=8 on the chip: eight optimizer updates per
        # dispatch under lax.scan — the tunneled chip's per-dispatch
        # round trip bounds throughput, so amortizing it is the cheapest
        # 'more samples/sec' there is.
        GNNTrainConfig(batch_size=batch, epochs=1000, eval_fraction=0.02,
                       max_seconds=gnn_budget,
                       steps_per_call=steps_per_call,
                       progress_callback=on_progress,
                       compile_callback=on_compile,
                       eval_max_seconds=min(eval_reserve, 25.0)),
        mesh,
    )
    state.set_headline(gnn.samples_per_sec / mesh.n_data)
    state.record(
        gnn_f1=round(gnn.f1, 4),
        gnn_precision=round(gnn.precision, 4),
        gnn_recall=round(gnn.recall, 4),
        gnn_steps=gnn.steps,
        gnn_compile_seconds=round(gnn.compile_seconds, 1),
    )
    state.stage_done("gnn")


@stage("mlp", min_left=45.0, required=True, needs_device=True)
def stage_mlp(state: BenchState, ctx: dict) -> None:
    """MLP training throughput + honest registry mae from a
    really-trained model (budget-gated)."""
    left = ctx["left"]
    mesh = ctx["mesh"]

    from dragonfly2_tpu.train import MLPTrainConfig, train_mlp

    cluster = ctx.get("cluster")
    if cluster is None:
        from dragonfly2_tpu.data import SyntheticCluster

        cluster = ctx["cluster"] = SyntheticCluster(n_hosts=2000, seed=0)
    X, y = cluster.pair_example_columns(300_000)
    mlp = train_mlp(
        X, y,
        MLPTrainConfig(epochs=100, batch_size=16384,
                       max_seconds=max(min(left() - 25.0, 25.0), 2.0),
                       progress_callback=lambda s, r: state.record(
                           mlp_train_samples_per_sec_per_chip=int(
                               r / mesh.n_data)),
                       compile_callback=lambda c: state.record(
                           mlp_compile_seconds=round(c, 1))),
        mesh,
    )
    state.record(
        mlp_train_samples_per_sec_per_chip=int(
            mlp.samples_per_sec / mesh.n_data),
        mlp_eval_mae_mbps=round(mlp.mae, 3),
    )
    state.stage_done("mlp")


@stage("dataplane", min_left=12.0)
def stage_dataplane(state: BenchState, ctx: dict) -> None:
    """Data plane — three rungs:

    1. the PR-3 coalesce ladder (loopback back-to-source with the
       amortization counters; run=1 is the one-GET-per-piece baseline),
    2. the ISSUE-7 upload-loopback rung — the event-loop serving engine
       with the serve path pinned to pure-Python os.sendfile (native
       off), bound ≥ UPLOAD_SPEEDUP_BOUND× the persisted 134 MB/s
       thread-per-conn baseline,
    3. the concurrency-density rung — ≥256 concurrent keep-alive piece
       streams against one seed, every body md5-verified, server thread
       count bounded at a CONSTANT (the threaded engine held ~1 thread
       per connection),
    4. the ISSUE-15 DOWNLOAD density rung — 8/32/128 concurrent tasks
       against ONE real daemon on the async download engine, download
       threads bounded at dl_workers+2 at every rung (the threaded
       engine grew with task count) and the 128-task aggregate MB/s ≥
       a same-process thread-engine baseline,
    5. the ISSUE-16 DOWNLOAD SPLICE rung — PieceFetchOp bodies landing
       via the native socket→pwrite splice (zero-copy, no inline
       digest), every piece span md5-verified post-window, bound ≥
       SPLICE_BOUND_MB_S (1.5× the 536 MB/s native upload record),
    6. the ISSUE-16 TLS rungs — upload loopback and the ≥256-stream
       density rung repeated over nonblocking TLS (same serving engine,
       same constant thread census), with the handshake/fallback
       counters recorded; skipped explicitly when the openssl CLI
       can't mint certs.

    A green run (all verdicts) persists to
    artifacts/bench_state/dataplane_run_<tag>.json — the record
    `bench.py dataplane --check-regression` gates future PRs against."""
    left = ctx["left"]

    from dragonfly2_tpu.client.dataplane import run_loopback_bench
    from dragonfly2_tpu.client.uploadbench import (
        UPLOAD_SPEEDUP_BOUND,
        run_density_rung,
        run_upload_loopback_bench,
    )

    ladder = {}
    for run in (1, 8):
        ladder[run] = run_loopback_bench(
            64 << 20, coalesce_run=run, workers=4)
    best = ladder[8]
    state.record(
        dataplane_loopback_mb_per_s=best["mb_per_s"],
        dataplane_pieces=best["pieces"],
        dataplane_requests_saved=best["requests_saved"],
        dataplane_connections_opened=best["connections_opened"],
        dataplane_connections_reused=best["connections_reused"],
        dataplane_coalesce_run_p50=best["coalesce_run_p50"],
        dataplane_report_rpcs_saved=best["report_rpcs_saved"],
        dataplane_ladder={
            str(run): {k: v[k] for k in (
                "mb_per_s", "seconds", "source_requests",
                "source_pieces", "requests_saved",
                "connections_opened", "connections_reused",
                "server_connections", "server_requests",
                "coalesce_run_p50")}
            for run, v in ladder.items()},
    )
    if left() < 10.0:
        # Same contract as the budget-skipped kill rung: a skip must
        # never read as a verified pass.
        state.record(dataplane_upload_rungs_skipped=True)
        state.stage_done("dataplane")
        return
    upload = run_upload_loopback_bench(
        timeout_s=max(min(left() * 0.5, 45.0), 8.0))
    upload_pass = bool(
        upload["md5_ok"]
        and upload["speedup_vs_baseline"] >= UPLOAD_SPEEDUP_BOUND)
    state.record(
        dataplane_upload_mb_per_s=upload["mb_per_s"],
        dataplane_upload_attempts=upload["attempt_mb_per_s"],
        dataplane_upload_speedup=upload["speedup_vs_baseline"],
        dataplane_upload_speedup_bound=upload["speedup_bound"],
        dataplane_upload_serve_path=upload["serve_path"],
        dataplane_upload_server_threads=upload["server_threads"],
        dataplane_upload_verdict_pass=upload_pass,
    )
    if left() < 8.0:
        # The upload rung ate the remaining budget: a starved density
        # rung would go incomplete and record a False verdict that
        # reads as a perf regression. Record the skip explicitly; the
        # combined verdict below then covers the upload rung only, and
        # nothing persists as a full green.
        state.record(dataplane_density_skipped=True,
                     dataplane_verdict_pass=upload_pass)
        state.stage_done("dataplane")
        return
    density = run_density_rung(timeout_s=max(min(left() * 0.7, 60.0), 10.0))
    state.record(
        dataplane_density_streams=density["streams"],
        dataplane_density_mb_per_s=density["mb_per_s"],
        dataplane_density_p99_ms=density["time_to_piece_p99_ms"],
        dataplane_density_server_threads=density["server_threads"],
        dataplane_density_thread_bound=density["server_thread_bound"],
        dataplane_density_md5_ok=density["md5_ok"],
        dataplane_density_verdict_pass=density["verdict_pass"],
    )
    if left() < 12.0:
        # Budget-starved download rung: record the skip explicitly so
        # it never reads as a pass OR a regression, and persist nothing
        # (a record without the download rung would let the
        # check-regression gate grade against a partial green).
        state.record(dataplane_dl_density_skipped=True,
                     dataplane_verdict_pass=bool(
                         upload_pass and density["verdict_pass"]))
        state.stage_done("dataplane")
        return
    from dragonfly2_tpu.client.dataplane import run_download_density_rung

    dl_density = run_download_density_rung(
        timeout_s=max(min(left() * 0.8, 120.0), 12.0))
    state.record(
        dataplane_dl_density_top_mb_per_s=dl_density["top_rung_mb_per_s"],
        dataplane_dl_density_thread_bound=dl_density["thread_bound"],
        dataplane_dl_density_threads_bounded=dl_density["threads_bounded"],
        dataplane_dl_density_vs_thread_engine=dl_density.get(
            "vs_thread_engine"),
        dataplane_dl_density_rungs={
            n: {k: v for k, v in r.items() if k != "census_peak"}
            for n, r in dl_density["rungs"].items()},
        dataplane_dl_density_verdict_pass=dl_density["verdict_pass"],
    )
    base_pass = bool(upload_pass and density["verdict_pass"]
                     and dl_density["verdict_pass"])
    if left() < 8.0:
        # Budget-starved splice/TLS rungs: explicit skip, partial
        # verdict, nothing persists as a full green.
        state.record(dataplane_splice_skipped=True,
                     dataplane_tls_rungs_skipped=True,
                     dataplane_verdict_pass=base_pass)
        state.stage_done("dataplane")
        return
    from dragonfly2_tpu.client.dataplane import run_splice_loopback_bench

    splice = run_splice_loopback_bench(
        timeout_s=max(min(left() * 0.4, 45.0), 8.0))
    if splice.get("skipped"):
        state.record(dataplane_splice_skipped=True,
                     dataplane_splice_skip_reason=splice["reason"],
                     dataplane_verdict_pass=base_pass)
        state.stage_done("dataplane")
        return
    state.record(
        dataplane_splice_mb_per_s=splice["mb_per_s"],
        dataplane_splice_bound_mb_per_s=splice["bound_mb_per_s"],
        dataplane_splice_bytes=splice["splice_bytes"],
        dataplane_splice_zero_copy_fraction=splice.get(
            "zero_copy_fraction", 0.0),
        dataplane_splice_verified_pieces=splice.get("verified_pieces", 0),
        dataplane_splice_verdict_pass=splice["verdict_pass"],
    )
    if left() < 10.0:
        state.record(dataplane_tls_rungs_skipped=True,
                     dataplane_verdict_pass=bool(
                         base_pass and splice["verdict_pass"]))
        state.stage_done("dataplane")
        return
    tls_upload = run_upload_loopback_bench(
        size_bytes=128 << 20, attempts=2, tls=True,
        timeout_s=max(min(left() * 0.4, 40.0), 8.0))
    if tls_upload.get("skipped"):
        state.record(dataplane_tls_rungs_skipped=True,
                     dataplane_tls_skip_reason=tls_upload["reason"],
                     dataplane_verdict_pass=bool(
                         base_pass and splice["verdict_pass"]))
        state.stage_done("dataplane")
        return
    tls_density = run_density_rung(
        tls=True, timeout_s=max(min(left() * 0.7, 60.0), 10.0))
    tls_pass = bool(tls_upload["md5_ok"]
                    and tls_upload["tls_handshakes"] > 0
                    and tls_density.get("verdict_pass"))
    state.record(
        dataplane_tls_upload_mb_per_s=tls_upload["mb_per_s"],
        dataplane_tls_upload_md5_ok=tls_upload["md5_ok"],
        dataplane_tls_handshakes=tls_upload["tls_handshakes"],
        dataplane_tls_fallbacks=tls_upload["tls_fallbacks"],
        dataplane_tls_ktls_bytes=tls_upload["ktls_bytes"],
        dataplane_tls_density_streams=tls_density.get("streams"),
        dataplane_tls_density_mb_per_s=tls_density.get("mb_per_s"),
        dataplane_tls_density_server_threads=tls_density.get(
            "server_threads"),
        dataplane_tls_density_verdict_pass=tls_density.get(
            "verdict_pass"),
        dataplane_tls_verdict_pass=tls_pass,
    )
    verdict = bool(base_pass and splice["verdict_pass"] and tls_pass)
    state.record(dataplane_verdict_pass=verdict)
    state.stage_done("dataplane")
    if verdict:
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"dataplane_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"ladder": {str(k): v for k, v in ladder.items()},
             "upload_loopback": upload,
             "density": density,
             "download_density": dl_density,
             "download_splice": splice,
             "tls_upload": tls_upload,
             "tls_density": tls_density})


@stage("scheduler", min_left=15.0)
def stage_scheduler(state: BenchState, ctx: dict) -> None:
    """Scheduler control plane — two ladders:

    1. the in-process swarm ladder against one real SchedulerService
       (sharded managers + incremental GC + O(1) peer statistics), now
       extended to a 25k single-replica rung when budget allows, each
       rung reporting the peak-RSS + bytes/peer gauges next to the
       pre-slimming baseline;
    2. the ISSUE-11 CLUSTER ladder (scheduler/clusterbench.py): a
       4-replica subprocess cluster driven over real gRPC through the
       BalancedSchedulerClient, baseline rung + big rung with a
       mid-swarm replica SIGKILL, bounding announce p99 across the
       cluster by the same LADDER_P99_BOUND and the re-route p99 by
       the chaos-plane grace.

    Budget-starved rungs record explicit skips (never a silent pass);
    a green run persists to artifacts/bench_state/scheduler_run_*.json
    — the record `bench.py scheduler --check-regression` gates against.
    `--rungs` / `--cluster-peers` override the shapes from the CLI."""
    left = ctx["left"]

    from dragonfly2_tpu.scheduler.loadbench import run_swarm_ladder

    if ctx.get("rungs"):
        sizes = tuple(ctx["rungs"])
    elif left() > 240.0:
        sizes = (100, 1000, 5000, 25000)
    elif left() > 30.0:
        sizes = (100, 1000, 5000)
    else:
        sizes = (100, 500, 1500)
    sched = run_swarm_ladder(sizes, workers=8)
    ladder = sched["ladder"]
    largest = ladder[str(sizes[-1])]
    state.record(
        scheduler_swarm_sizes=list(sizes),
        scheduler_announce_p50_ms=largest["announce_p50_ms"],
        scheduler_announce_p99_ms=largest["announce_p99_ms"],
        scheduler_decisions_per_sec=largest["decisions_per_sec"],
        scheduler_piece_reports_per_sec=largest[
            "piece_reports_per_sec"],
        scheduler_gc_pause_p99_ms=largest["gc_pause_p99_ms"],
        scheduler_gc_budget_overruns=largest["gc_budget_overruns"],
        scheduler_bad_node_fast=largest["bad_node_fast"],
        scheduler_bad_node_slow=largest["bad_node_slow"],
        scheduler_peak_rss_mb=largest["peak_rss_mb"],
        scheduler_bytes_per_peer=largest["bytes_per_peer"],
        scheduler_bytes_per_peer_pre_slim=largest[
            "bytes_per_peer_pre_slim_baseline"],
        scheduler_decision_p99_ratio=sched["decision_p99_ratio"],
        scheduler_ladder_p99_bound=sched["ladder_p99_bound"],
        scheduler_p99_within_bound=sched["p99_within_bound"],
        scheduler_ladder={
            size: {k: v[k] for k in (
                "seconds", "announce_p50_ms", "announce_p99_ms",
                "decisions", "decisions_per_sec", "piece_reports",
                "piece_reports_per_sec", "back_to_source",
                "filter_ms_p99", "evaluate_ms_p99", "gc_ticks",
                "gc_pause_p50_ms", "gc_pause_p99_ms",
                "gc_budget_overruns", "gc_reclaimed", "peak_rss_mb",
                "peak_rss_scope", "rss_delta_mb", "bytes_per_peer",
                "bytes_per_peer_pre_slim_baseline", "tasks",
                "peers_per_task", "workers", "errors")}
            for size, v in ladder.items()},
    )

    # -- cluster ladder (multi-process, real gRPC) ----------------------
    # The full 100k rung is a ~10-minute drive on a small box; scale the
    # rung to the remaining budget and record the scale explicitly. The
    # persisted 100k green run comes from `BENCH_BUDGET_S=1800 bench.py
    # scheduler` (or --cluster-peers 100000).
    cluster = None
    # In a FULL bench run the chaos/fanout stages still need their
    # budget after this one — the cluster ladder may claim only a
    # share of what's left; a single-stage `bench.py scheduler` run
    # owns the whole budget.
    cluster_budget = (left() - 25.0 if ctx.get("single_stage")
                      else min(left() * 0.3, 240.0))
    if ctx.get("cluster_peers") is not None:
        cluster_peers = int(ctx["cluster_peers"])
    elif cluster_budget > 1000.0:
        cluster_peers = 100_000
    elif cluster_budget > 400.0:
        cluster_peers = 20_000
    elif cluster_budget > 150.0:
        cluster_peers = 4_000
    else:
        cluster_peers = 0
    if cluster_peers <= 0:
        state.record(scheduler_cluster_skipped=True)
    else:
        from dragonfly2_tpu.scheduler.clusterbench import run_cluster_ladder

        cluster = run_cluster_ladder(
            cluster_peers=cluster_peers, replicas=4,
            kill_replica=True,
            deadline_s=max(min(cluster_budget, left() - 25.0), 30.0))
        big = cluster.get("cluster")
        state.record(
            scheduler_cluster_peers=cluster_peers,
            scheduler_cluster_baseline_p99_ms=cluster["baseline"][
                "announce_p99_ms"],
            scheduler_cluster_baseline_samples=cluster["baseline"][
                "samples"],
        )
        if big is not None:
            state.record(
                scheduler_cluster_replicas=big["replicas"],
                scheduler_cluster_seconds=big["seconds"],
                scheduler_cluster_announce_p50_ms=big["announce_p50_ms"],
                scheduler_cluster_announce_p99_ms=big["announce_p99_ms"],
                scheduler_cluster_decisions_per_sec=big[
                    "decisions_per_sec"],
                scheduler_cluster_success_rate=big["success_rate"],
                scheduler_cluster_bytes_per_peer=big[
                    "bytes_per_peer_cluster"],
                scheduler_cluster_p99_ratio=cluster.get(
                    "cluster_p99_ratio"),
                scheduler_cluster_p99_bound=cluster["ladder_p99_bound"],
                scheduler_cluster_kill=big.get("killed"),
                scheduler_cluster_reroutes=big.get("reroutes"),
                scheduler_cluster_reroute_p99_ms=big.get("reroute_p99_ms"),
                scheduler_cluster_reroute_bound_s=big.get(
                    "reroute_bound_s"),
                scheduler_cluster_sessions_rehomed=big.get(
                    "sessions_rehomed"),
                scheduler_cluster_kill_verdict_pass=big.get(
                    "kill_verdict_pass"),
                scheduler_cluster_recovery=big["recovery_counters"],
                scheduler_cluster_failovers=big["recovery_counters"][
                    "scheduler_failovers"],
                scheduler_cluster_per_replica=big["per_replica"],
            )
        if cluster.get("verdict_skipped_budget"):
            state.record(scheduler_cluster_verdict_skipped=True)
        else:
            state.record(
                scheduler_cluster_p99_within_bound=cluster[
                    "p99_within_bound"],
                scheduler_cluster_verdict_pass=cluster["verdict_pass"])

    ladder_green = bool(sched["p99_within_bound"]
                        and not largest["errors"])
    # A budget-skipped cluster ladder is an EXPLICIT skip (recorded
    # above), not a failure: the overall verdict covers what ran — the
    # same contract as cluster_peers=0. Only an actually-failed cluster
    # verdict turns the run red.
    cluster_skipped = (cluster is not None
                      and bool(cluster.get("verdict_skipped_budget")))
    cluster_green = (cluster is not None
                     and cluster.get("verdict_pass") is True)
    green = bool(ladder_green
                 and (cluster is None or cluster_skipped or cluster_green))
    state.record(scheduler_verdict_pass=green)
    state.stage_done("scheduler")
    if green:
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"scheduler_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"ladder": sched,
             "cluster": (cluster if cluster is not None
                         and not cluster_skipped
                         else {"skipped": True})})


@stage("chaos", min_left=15.0)
def stage_chaos(state: BenchState, ctx: dict) -> None:
    """Chaos — deterministic fault-injection ladder over the loopback
    swarm (scheduler + two peers + origin, client/chaosbench.py), the
    same ladder repeated with every p2p leg over TLS plus mid-handshake
    resets in the mix (ISSUE 16), plus the ISSUE-6 scheduler-kill rung (three scheduler replica PROCESSES,
    one hard-killed mid-swarm by the seeded ``scheduler.process`` site)
    and the ISSUE-8 daemon-kill rung (a daemon process SIGKILLed at
    ~50% of a download, restarted on the same storage root).
    Ladder bound (docs/CHAOS.md): 100% task success at every rung and
    ≥70% goodput retention at the 5% rung. Scheduler-kill bound: 100%
    task success, p99 re-route ≤ scheduler_grace, 0 tasks degraded to
    back-to-source while ≥1 replica survives. Daemon-kill bound: 100%
    task success, md5-exact final bytes, re-downloaded bytes ≤ missing
    + one piece per worker, restarted seed re-announces and serves.
    The combined verdict lands in the bench JSON, and a passing run
    persists into artifacts/bench_state/ like the TPU runs do."""
    left = ctx["left"]

    from dragonfly2_tpu.client.chaosbench import (
        run_chaos_ladder,
        run_daemon_kill_rung,
        run_scheduler_kill_rung,
    )

    chaos = run_chaos_ladder(seed=0)
    top = chaos["ladder"][str(max(chaos["rates"]))]
    tls_chaos = None
    if left() <= 12.0:
        state.record(chaos_tls_ladder_skipped=True)
    else:
        # The same ladder with every p2p leg over TLS and mid-handshake
        # resets added to the fault mix (ISSUE 16) — skipped explicitly
        # when the openssl CLI can't mint a throwaway CA.
        tls_chaos = run_chaos_ladder(seed=0, tls=True)
        if tls_chaos.get("skipped"):
            state.record(chaos_tls_ladder_skipped=True,
                         chaos_tls_skip_reason=tls_chaos["reason"])
            tls_chaos = None
        else:
            tls_top = tls_chaos["ladder"][str(max(tls_chaos["rates"]))]
            state.record(
                chaos_tls_success_rate_at_max=tls_top["success_rate"],
                chaos_tls_goodput_retention_at_max=tls_chaos[
                    "goodput_retention_at_max"],
                chaos_tls_recovery_events=tls_top["recovery_events"],
                chaos_tls_handshake_faults=(tls_top.get("faults", {})
                                            .get("tls.handshake")),
                chaos_tls_all_rungs_full_success=tls_chaos[
                    "all_rungs_full_success"],
                chaos_tls_verdict_pass=tls_chaos["verdict_pass"],
            )
    state.record(
        chaos_rates=chaos["rates"],
        chaos_success_rate_at_max=top["success_rate"],
        chaos_goodput_retention_at_max=chaos[
            "goodput_retention_at_max"],
        chaos_goodput_retention_bound=chaos[
            "goodput_retention_bound"],
        chaos_recovery_p50_ms=top["recovery_p50_ms"],
        chaos_recovery_p99_ms=top["recovery_p99_ms"],
        chaos_recovery_events=top["recovery_events"],
        chaos_all_rungs_full_success=chaos[
            "all_rungs_full_success"],
        chaos_ladder={
            rate: {k: v[k] for k in (
                "success_rate", "downloads", "mb_per_s",
                "seconds", "recovery_events", "recovery_p50_ms",
                "recovery_p99_ms", "download_p99_s")}
            for rate, v in chaos["ladder"].items()},
    )
    kill = None
    if left() <= 8.0:
        # A skipped kill rung must never read as a verified pass: the
        # combined verdict below then covers the LADDER ONLY, and both
        # the bench JSON and the persisted artifact say so explicitly
        # (chaos_scheduler_kill_verdict_pass stays absent — a driver
        # gating on it sees a miss, not a green).
        state.record(chaos_scheduler_kill_skipped=True)
    else:
        kill = run_scheduler_kill_rung(seed=0)
        state.record(
            chaos_scheduler_kill_success_rate=kill["success_rate"],
            chaos_scheduler_kill_reroutes=kill["reroutes"],
            chaos_scheduler_kill_reroute_p50_ms=kill["reroute_p50_ms"],
            chaos_scheduler_kill_reroute_p99_ms=kill["reroute_p99_ms"],
            chaos_scheduler_kill_reroute_bound_s=kill["reroute_bound_s"],
            chaos_scheduler_kill_failovers=kill["failovers"],
            chaos_scheduler_kill_pieces_replayed=kill["pieces_replayed"],
            chaos_scheduler_kill_degraded=kill["degraded_to_source"],
            chaos_scheduler_kill_verdict_pass=kill["verdict_pass"],
        )
    daemon_kill = None
    if left() <= 8.0:
        # Same contract as a budget-skipped scheduler-kill rung: the
        # skip is recorded explicitly (never a silent pass) and the
        # persisted artifact says {"skipped": true}.
        state.record(chaos_daemon_kill_skipped=True)
    else:
        daemon_kill = run_daemon_kill_rung(seed=0)
        state.record(
            chaos_daemon_kill_success_rate=daemon_kill["success_rate"],
            chaos_daemon_kill_killed=daemon_kill["killed"],
            chaos_daemon_kill_resumed_pieces=daemon_kill.get(
                "resume", {}).get("resumed_pieces"),
            chaos_daemon_kill_bytes_fresh=daemon_kill.get(
                "resume", {}).get("bytes_fresh"),
            chaos_daemon_kill_refetch_bound=daemon_kill.get(
                "refetch_bound_bytes"),
            chaos_daemon_kill_reseed=daemon_kill.get("reseed"),
            chaos_daemon_kill_failures=daemon_kill["failures"][:5],
            chaos_daemon_kill_verdict_pass=daemon_kill["verdict_pass"],
        )
    verdict = bool(chaos["verdict_pass"]
                   and (tls_chaos is None or tls_chaos["verdict_pass"])
                   and (kill is None or kill["verdict_pass"])
                   and (daemon_kill is None
                        or daemon_kill["verdict_pass"]))
    state.record(chaos_verdict_pass=verdict)
    state.stage_done("chaos")
    if verdict:
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"chaos_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"ladder": chaos,
             "tls_ladder": (tls_chaos if tls_chaos is not None
                            else {"skipped": True}),
             "scheduler_kill": (kill if kill is not None
                                else {"skipped": True}),
             "daemon_kill": (daemon_kill if daemon_kill is not None
                             else {"skipped": True})})


@stage("mlguard")
def stage_mlguard(state: BenchState, ctx: dict) -> None:
    """Guarded model lifecycle — the ISSUE-12 poisoned-model rung
    (dragonfly2_tpu/inference/guardbench.py): a live loopback swarm
    scheduling through the ML serving stack (RemoteMLEvaluator → gRPC
    sidecar → manager registry, reload watcher running) while a
    NaN-poisoned model is published three ways: through the validation
    gate (must be quarantined OFFLINE, replaying announce traces
    recorded from this very swarm), force-published into SHADOW mode
    (canary must reject + quarantine it with the incumbent never
    leaving the decision path), and force-published LIVE with shadow
    off (the runtime guard must degrade every poisoned batch to rules,
    escalate to a manager quarantine, and the watcher must restore the
    previous version). Documented bounds (docs/CHAOS.md): 100 % task
    success, decision quality never below the rule baseline, rollback
    within 2 × reload_interval of exposure. A green run persists to
    artifacts/bench_state/mlguard_run_*.json; a budget-skipped rung
    records an explicit skip artifact — never a silent pass."""
    left = ctx["left"]

    from dragonfly2_tpu.inference.guardbench import run_mlguard_rung

    # The budget gate lives HERE (no registry min_left): a registry-level
    # skip would record nothing — this branch records the skip and
    # persists a {"skipped": true} artifact the record scan ignores.
    # An explicitly requested single stage always runs.
    if left() < 60.0 and not ctx.get("single_stage"):
        state.record(mlguard_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"mlguard_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        return
    rung = run_mlguard_rung(seed=0)
    state.record(
        mlguard_downloads=rung["downloads"],
        mlguard_success_rate=rung["success_rate"],
        mlguard_failures=rung["failures"][:5],
        mlguard_gate_rejected=rung["gate"]["rejected_offline"],
        mlguard_gate_trace_source=rung["gate"]["trace_source"],
        mlguard_shadow_rollback_s=rung["shadow_phase"]["rollback_s"],
        mlguard_shadow_incumbent_held=rung["shadow_phase"][
            "incumbent_held"],
        mlguard_guard_rollback_s=rung["guard_phase"]["rollback_s"],
        mlguard_rollback_bound_s=rung["rollback_bound_s"],
        mlguard_guard_trips=rung["counters"].get("ml_guard_trips"),
        mlguard_quality_mean=rung["quality_mean"],
        mlguard_quality_min=rung["quality_min"],
        mlguard_quarantines=rung["counters"].get("model_quarantines"),
        mlguard_rollbacks=rung["counters"].get("model_rollbacks"),
        mlguard_error=rung.get("error"),
        mlguard_verdict_pass=rung["verdict_pass"],
    )
    state.stage_done("mlguard")
    if rung["verdict_pass"]:
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"mlguard_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            rung)


@stage("replay")
def stage_replay(state: BenchState, ctx: dict) -> None:
    """Replay plane — the ISSUE-13 decision-quality A/B
    (dragonfly2_tpu/scheduler/replaybench.py): record a profiled-cost
    swarm's full announce decision stream (candidates + features +
    realized Welford costs + outcomes) into the rotating replay
    dataset, train a learned piece-cost model + a bandwidth MLP on the
    corpus, push both through the PR-12 validation gate, and replay
    the corpus head-to-head through rule vs ML vs learned-cost
    evaluators — reporting realized-cost regret, rank agreement,
    bad-node precision/recall and per-decision latency. Determinism is
    asserted (same corpus + seed ⇒ bit-identical decision sequence,
    each evaluator replayed twice), and the recorder overhead guard
    bounds announce p99 with the recorder ON within 5% of OFF
    (docs/REPLAY.md). A green run persists to
    artifacts/bench_state/replay_run_*.json — the record `bench.py
    replay --check-regression` reads; budget-starved runs record an
    explicit skip artifact, never a silent pass.

    The stage then climbs the vectorized replay throughput ladder
    (run_replay_throughput_ladder): synthetic columnar corpora at the
    10k/100k rungs, sequential vs whole-corpus vectorized vs sharded
    scoring — bit-identical digests required at every rung and the
    vectorized path ≥ 20× sequential on the 100k rung. A green ladder
    persists to replay_ladder_run_*.json (the throughput record
    --check-regression compares against); the same budget-skip
    artifact rule applies."""
    left = ctx["left"]

    from dragonfly2_tpu.scheduler.replaybench import (
        run_replay_ab, run_replay_throughput_ladder)

    # Budget gate inside the stage (the mlguard lesson): a registry
    # min_left skip would record nothing.
    if left() < 120.0 and not ctx.get("single_stage"):
        state.record(replay_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"replay_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        return
    report = run_replay_ab(seed=0)
    evaluators = (report.get("ab") or {}).get("evaluators") or {}
    state.record(
        replay_corpus_decisions=(report.get("record") or {}).get(
            "corpus_decisions"),
        replay_gate={name: g.get("state")
                     for name, g in (report.get("gate") or {}).items()},
        replay_deterministic=(report.get("ab") or {}).get("deterministic"),
        replay_regret_mean_s={name: s.get("regret_mean_s")
                              for name, s in evaluators.items()},
        replay_rank_agreement={name: s.get("rank_agreement_mean")
                               for name, s in evaluators.items()},
        replay_bad_node={name: {"precision": s.get("bad_node_precision"),
                                "recall": s.get("bad_node_recall")}
                         for name, s in evaluators.items()},
        replay_decision_latency_p99_ms={
            name: s.get("decision_latency_p99_ms")
            for name, s in evaluators.items()},
        replay_regret_within_bound=report.get("regret_within_bound"),
        replay_recorder_overhead_ratio=(report.get("recorder_overhead")
                                        or {}).get("p99_ratio"),
        replay_recorder_overhead_ok=(report.get("recorder_overhead")
                                     or {}).get("within_bound"),
        replay_error=report.get("error"),
        replay_verdict_pass=report.get("verdict_pass"),
    )
    if report.get("verdict_pass"):
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"replay_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            report)

    # Throughput ladder — same budget-skip discipline as the A/B: a
    # starved run leaves an explicit skip artifact, never nothing.
    if left() < 60.0 and not ctx.get("single_stage"):
        state.record(replay_ladder_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"replay_ladder_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        state.stage_done("replay")
        return
    ladder = run_replay_throughput_ladder()
    bound_rung = next(
        (r for r in ladder.get("rungs", ())
         if r.get("decisions") == ladder.get("bound_rung")), {})
    state.record(
        replay_ladder_rungs=[r.get("decisions")
                             for r in ladder.get("rungs", ())],
        replay_ladder_digests_equal=all(
            r.get("digests_equal") for r in ladder.get("rungs", ())),
        replay_ladder_seq_decisions_per_s=bound_rung.get(
            "seq_decisions_per_s"),
        replay_ladder_vec_decisions_per_s=bound_rung.get(
            "vec_decisions_per_s"),
        replay_ladder_speedup=bound_rung.get("speedup"),
        replay_ladder_sharded_speedup=bound_rung.get("sharded_speedup"),
        replay_ladder_bound=ladder.get("bound"),
        replay_ladder_error=ladder.get("error"),
        replay_ladder_verdict_pass=ladder.get("verdict_pass"),
    )
    state.stage_done("replay")
    if ladder.get("verdict_pass"):
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"replay_ladder_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            ladder)


@stage("obs")
def stage_obs(state: BenchState, ctx: dict) -> None:
    """Observability plane — the ISSUE-14 fleet-tracing stage
    (dragonfly2_tpu/client/obsbench.py): a live loopback swarm under a
    tail-sampling tracer with a ZERO head fraction. The clean warm-up
    task's trace must be dropped; a task disrupted by a seeded
    mid-download piece-body STALL must breach the SLO and be
    tail-captured END TO END (daemon + scheduler spans, one trace id),
    with the critical-path analyzer naming the injected stall as the
    dominant contributor; every registered /debug/vars stats block must
    scrape at /metrics in Prometheus text format; and the overhead
    guards must hold tracing-on within 1.05× of tracing-off on both
    the announce p99 and loopback MB/s (docs/OBSERVABILITY.md). A
    green run persists to artifacts/bench_state/obs_run_*.json; a
    budget-skipped stage records an explicit skip artifact, never a
    silent pass."""
    left = ctx["left"]

    from dragonfly2_tpu.client.obsbench import run_obs_stage

    # Budget gate inside the stage (the mlguard lesson): a registry
    # min_left skip would record nothing.
    if left() < 90.0 and not ctx.get("single_stage"):
        state.record(obs_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"obs_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        return
    report = run_obs_stage(seed=0)
    rung = report["rung"]
    state.record(
        obs_warm_trace_dropped=rung.get("warm_trace_dropped"),
        obs_disrupted_ttlb_s=rung.get("disrupted_ttlb_s"),
        obs_tail_reasons=rung.get("tail_reasons"),
        obs_dominant=(rung.get("analyzer") or {}).get("dominant"),
        obs_metrics_blocks=(rung.get("metrics_scrape") or {}).get(
            "blocks"),
        obs_metrics_all_exported=(rung.get("metrics_scrape") or {}).get(
            "all_blocks_exported"),
        obs_announce_p99_ratio=report["announce_guard"].get("p99_ratio"),
        obs_announce_within_bound=report["announce_guard"].get(
            "within_bound"),
        obs_loopback_ratio=report["loopback_guard"].get(
            "throughput_ratio"),
        obs_loopback_within_bound=report["loopback_guard"].get(
            "within_bound"),
        obs_failures=rung.get("failures", [])[:5],
        obs_verdict_pass=report["verdict_pass"],
    )
    state.stage_done("obs")
    if report["verdict_pass"]:
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"obs_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            report)


@stage("qos")
def stage_qos(state: BenchState, ctx: dict) -> None:
    """Multi-tenant QoS plane — the ISSUE-17 weighted-fair admission
    stage (dragonfly2_tpu/client/qosbench.py): a throttled seed serves
    interactive + bulk + background classed pulls CONCURRENTLY. The
    mixed rung gates interactive per-task p99 within its documented
    bound while bulk keeps ≥ 70% of its single-class saturation
    throughput; the flooding-tenant chaos rung gates that a background
    flood's 503 sheds land exclusively on the flooder and interactive
    still holds its (looser) bound (docs/QOS.md). A green run persists
    to artifacts/bench_state/qos_run_*.json; a budget-skipped stage
    records an explicit skip artifact, never a silent pass."""
    left = ctx["left"]

    from dragonfly2_tpu.client.qosbench import run_qos_stage

    # Budget gate inside the stage (the mlguard lesson): a registry
    # min_left skip would record nothing.
    if left() < 45.0 and not ctx.get("single_stage"):
        state.record(qos_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"qos_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        return
    report = run_qos_stage(seed=0)
    mixed, flood = report["mixed"], report["flood"]
    state.record(
        qos_interactive_p99_s=mixed.get("interactive_p99_s"),
        qos_interactive_p99_bound_s=mixed.get("interactive_p99_bound_s"),
        qos_bulk_alone_mb_per_s=mixed.get("bulk_alone_mb_per_s"),
        qos_bulk_mixed_mb_per_s=mixed.get("bulk_mixed_mb_per_s"),
        qos_bulk_fraction=mixed.get("bulk_fraction"),
        qos_upload_admitted_by_class=mixed.get(
            "upload_admitted_by_class"),
        qos_flood_interactive_p99_s=flood.get("interactive_p99_s"),
        qos_flood_shed_by_class=flood.get("upload_shed_by_class"),
        qos_flood_completed=flood.get("flood_completed"),
        qos_failures=(mixed.get("failures", [])
                      + flood.get("failures", []))[:5],
        qos_verdict_pass=report["verdict_pass"],
    )
    state.stage_done("qos")
    if report["verdict_pass"]:
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"qos_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            report)


@stage("fanout", min_left=90.0)
def stage_fanout(state: BenchState, ctx: dict) -> None:
    """Fleet-scale checkpoint fan-out — the ISSUE-9 dissemination
    ladder (client/fanoutbench.py): one throttled origin, a ≥256 MiB
    sharded checkpoint, cold fleet rungs of 4/16/32 in-process daemons
    plus a preheated variant at the largest rung. Reports
    time-to-last-byte per rung, origin-egress amplification, P2P share
    and per-daemon MB/s. Documented bounds (docs/FANOUT.md): cold
    amplification ≤ 2.0 at the 32-rung AND TTLB(32) ≤ 3× TTLB(4);
    preheated origin bytes ≈ 0. A green run persists to
    artifacts/bench_state/fanout_run_*.json — the record
    `bench.py fanout --check-regression` gates against. Budget-starved
    rungs record an explicit skip and withhold the verdict (never a
    silent pass)."""
    left = ctx["left"]

    from dragonfly2_tpu.client.fanoutbench import run_fanout_ladder

    ladder = run_fanout_ladder(seed=0, time_left=left)
    rungs = ladder["ladder"]
    largest = str(max(ladder["rungs"]))
    top = rungs.get(largest, {})
    state.record(
        fanout_rungs=ladder["rungs"],
        fanout_checkpoint_mb=ladder["checkpoint_bytes"] >> 20,
        fanout_origin_rate_mb_per_s=ladder["origin_rate_mb_per_s"],
        fanout_skipped_rungs=ladder["skipped_rungs"],
        fanout_ttlb_ratio=ladder.get("ttlb_ratio"),
        fanout_ttlb_ratio_bound=ladder["ttlb_ratio_bound"],
        fanout_cold_amplification=ladder.get("cold_amplification_at_max"),
        fanout_amplification_bound=ladder["amplification_bound"],
        fanout_cold_ttlb_s=top.get("ttlb_s"),
        fanout_cold_p2p_share=top.get("p2p_share"),
        fanout_per_daemon_mb_per_s_p50=top.get("per_daemon_mb_per_s_p50"),
        fanout_preheat_origin_fraction=ladder.get(
            "preheat_origin_fraction"),
        fanout_preheat_ttlb_s=(ladder.get("preheated") or {}).get(
            "ttlb_s"),
        fanout_ladder={
            n: {k: v.get(k) for k in (
                "ttlb_s", "origin_amplification", "p2p_share",
                "per_daemon_mb_per_s_p50", "per_daemon_mb_per_s_min",
                "success_rate", "origin_requests", "downloads",
                "failures")}
            for n, v in rungs.items()},
    )
    if "verdict_pass" in ladder:
        state.record(fanout_verdict_pass=ladder["verdict_pass"])
    state.stage_done("fanout")
    if ladder.get("verdict_pass"):
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"fanout_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            ladder)


@stage("geo")
def stage_geo(state: BenchState, ctx: dict) -> None:
    """Geo-hierarchical multi-site swarm — the ISSUE-18 WAN-aware
    routing ladder (client/geobench.py): three emulated sites of
    ``--cluster-id``-labeled daemon processes joined by seeded WAN
    link emulation (utils/geoplan.py), pulling a sharded checkpoint
    through scheduler-elected bridge peers. Gates (docs/GEO.md): cold
    WAN amplification ≤ 1 + #clusters at the largest rung with at
    least one bridge elected; cross-site preheat leaves the swarm
    phase WAN- and origin-quiet; the site-partition chaos rung's
    surviving sites finish 100% and the victim resumes crash-safe
    within the documented bound after heal. A green run persists to
    artifacts/bench_state/geo_run_*.json; a budget-skipped stage
    records an explicit skip artifact + ``geo_skipped``, never a
    silent pass."""
    left = ctx["left"]

    from dragonfly2_tpu.client.geobench import run_geo_ladder

    # Budget gate inside the stage (the mlguard lesson): a registry
    # min_left skip would record nothing.
    if left() < 120.0 and not ctx.get("single_stage"):
        state.record(geo_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"geo_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        return
    ladder = run_geo_ladder(seed=0, time_left=left)
    rungs = ladder["ladder"]
    largest = str(max(ladder["rungs"]))
    top = rungs.get(largest, {})
    partition = ladder.get("partition") or {}
    state.record(
        geo_sites=ladder["sites"],
        geo_rungs=ladder["rungs"],
        geo_checkpoint_mb=ladder["checkpoint_bytes"] >> 20,
        geo_skipped_rungs=ladder["skipped_rungs"],
        geo_wan_amplification=ladder.get("cold_wan_amplification_at_max"),
        geo_wan_amplification_bound=ladder["wan_amplification_bound"],
        geo_cold_ttlb_s=top.get("ttlb_s"),
        geo_site_ttlb_s=top.get("site_ttlb_s"),
        geo_bridge_grants=top.get("bridge_grants"),
        geo_bridge_denials=top.get("bridge_denials"),
        geo_origin_amplification=top.get("origin_amplification"),
        geo_preheat_wan_fraction=ladder.get("preheat_wan_fraction"),
        geo_preheat_origin_fraction=ladder.get(
            "preheat_origin_fraction"),
        geo_partition_survivor_success=partition.get(
            "survivor_success_rate"),
        geo_partition_resume_seconds=partition.get(
            "victim_resume_seconds"),
        geo_partition_resume_bound_s=partition.get("resume_bound_s"),
        geo_failures=(top.get("failures", [])
                      + partition.get("failures", []))[:5],
    )
    if "verdict_pass" in ladder:
        state.record(geo_verdict_pass=ladder["verdict_pass"])
    state.stage_done("geo")
    if ladder.get("verdict_pass"):
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"geo_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            ladder)


@stage("federated")
def stage_federated(state: BenchState, ctx: dict) -> None:
    """Byzantine-robust federated rounds — the ISSUE-20 stage
    (dragonfly2_tpu/train/fedbench.py): heterogeneous synthetic cluster
    corpora train a global bandwidth model through screened federated
    rounds (trainer/federation.py coordinator: norm/holdout/nonfinite
    admission screens, K-of-N quorum, durable round journal). Gates
    (docs/FEDERATED.md): the CLEAN rung's gate-promoted global must
    match-or-beat the best solo cluster model's replay-A/B regret on
    the mixed eval corpus, bit-deterministically; the POISONED rung's
    label-flipped/scaled cluster and NaN-params cluster must BOTH be
    screened every round, the persistent liar escalated to registry
    quarantine, and poisoned-fleet regret held within 1.2x clean; the
    COORDINATOR-KILL rung SIGKILLs a subprocess coordinator mid-round
    and must resume from the journal, committing the SAME round without
    retraining journaled clusters. A green run persists to
    artifacts/bench_state/federated_run_*.json; a budget-skipped stage
    records an explicit skip artifact + ``federated_skipped``, never a
    silent pass."""
    left = ctx["left"]

    from dragonfly2_tpu.train.fedbench import run_federated_bench

    # Budget gate inside the stage (the mlguard lesson): a registry
    # min_left skip would record nothing.
    if left() < 180.0 and not ctx.get("single_stage"):
        state.record(federated_skipped=True)
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"federated_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            {"skipped": True, "reason": "stage budget exhausted"})
        return
    # The kill rung costs two subprocess cold starts (~60s); drop it
    # when the budget is tight rather than losing the whole stage.
    report = run_federated_bench(seed=0,
                                 include_kill=bool(
                                     left() >= 300.0
                                     or ctx.get("single_stage")))
    clean, poisoned, kill = (report["clean"], report["poisoned"],
                             report["kill"])
    state.record(
        federated_rounds=len(clean.get("rounds", [])),
        federated_gate_state=clean.get("gate_state"),
        federated_regret_s=clean.get("federated_regret"),
        federated_best_solo_regret_s=clean.get("best_solo_regret"),
        federated_deterministic=clean.get("deterministic"),
        federated_clean_ok=clean.get("ok"),
        federated_screened_reasons=poisoned.get("screened_reasons"),
        federated_screens_ok=poisoned.get("screens_ok"),
        federated_escalated=poisoned.get("escalated"),
        federated_quarantined_version=poisoned.get("quarantined_version"),
        federated_poisoned_regret_s=poisoned.get("regret"),
        federated_within_poison_bound=poisoned.get("within_poison_bound"),
        federated_poisoned_ok=poisoned.get("ok"),
        federated_kill_ran=kill.get("ran"),
        federated_kill_resumed=kill.get("resumed"),
        federated_kill_no_retrain=kill.get("no_retrain"),
        federated_kill_ok=kill.get("ok"),
        federated_error=report.get("error"),
        federated_verdict_pass=report.get("verdict_pass"),
    )
    state.stage_done("federated")
    if report.get("verdict_pass"):
        _persist_json(
            os.path.join(
                STATE_DIR,
                f"federated_run_{time.strftime('%Y%m%d_%H%M%S')}.json"),
            report)


def run_stages(state: BenchState, platform: str, budget: float,
               only: str | None = None,
               stage_opts: dict | None = None) -> None:
    """Drive the registry. ``only`` runs a single named stage (plus the
    init stage when it needs a device) — the `bench.py <stage>` path.
    ``stage_opts`` carries CLI per-stage options (e.g. the scheduler
    stage's ``rungs``/``cluster_peers``) into the stage ctx."""
    t_start = time.perf_counter()

    def left() -> float:
        return budget - (time.perf_counter() - t_start)

    ctx: dict = {"platform": platform, "left": left,
                 "single_stage": only is not None}
    ctx.update(stage_opts or {})
    wanted = None
    if only is not None:
        by_name = {s.name: s for s in STAGES}
        if only not in by_name:
            raise SystemExit(
                f"unknown stage {only!r}; stages: {', '.join(by_name)}")
        wanted = by_name[only]
    for st in STAGES:
        if wanted is not None and st is not wanted:
            if not (st.name == "init" and wanted.needs_device):
                continue
        # An explicitly requested stage bypasses its budget gate — a
        # driver asking for `bench.py chaos` must get the stage (or its
        # error), never a silent skip that reads as pass.
        if st.min_left and left() < st.min_left and st is not wanted:
            continue
        if st.required and wanted is None:
            st.fn(state, ctx)  # a required stage failing fails the run
            continue
        # Everything else owes the driver the JSON line: record the
        # failure instead of dying before emit(). A failed required
        # stage here is single-stage init — skip the device stage it
        # was feeding.
        try:
            st.fn(state, ctx)
        except Exception as exc:  # noqa: BLE001
            state.record(**{f"{st.name}_error":
                            f"{type(exc).__name__}: {exc}"})
            if st.required and st is not wanted:
                break


def worker_main(platform: str, out_path: str, budget: float) -> None:
    state = BenchState(out_path)
    state.record(platform_requested=platform, worker_pid=os.getpid())

    t_start = time.perf_counter()

    def watchdog() -> None:
        # os._exit from this thread works even when the main thread is
        # blocked inside a hung device call (the tunnel-drop mode).
        while time.perf_counter() - t_start < budget:
            time.sleep(0.5)
        state.record(worker_watchdog_fired=True)
        state.flush()
        os._exit(0)

    threading.Thread(target=watchdog, daemon=True,
                     name="bench-worker-watchdog").start()
    try:
        run_stages(state, platform, budget - 3.0)
        state.record(worker_done=True)
    except BaseException as exc:  # noqa: BLE001 — persist, then re-raise
        state.record(worker_error=f"{type(exc).__name__}: {exc}")
        state.flush()
        raise
    state.flush()


# --------------------------------------------------------------------------
# Orchestrator.
# --------------------------------------------------------------------------

def probe_tpu(state: BenchState, timeout: float) -> bool:
    """Check — in a throwaway subprocess — that backend init completes
    and enumerates an accelerator."""
    code = ("import jax; ds = jax.devices(); "
            "print(ds[0].platform, len(ds))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        state.record(tpu_probe="timeout")
        return False
    if proc.returncode != 0:
        state.record(tpu_probe=f"rc={proc.returncode}")
        return False
    out = proc.stdout.strip().split()
    state.record(tpu_probe=" ".join(out))
    return bool(out) and out[0] not in ("cpu",)


def launch_worker(platform: str, out_path: str,
                  budget: float) -> subprocess.Popen:
    env = dict(os.environ)
    if platform != "tpu":
        env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", platform,
         out_path, f"{budget:.1f}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)


def read_state(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def persist_tpu_run(tpu_path: str, run_tag: str) -> None:
    """Copy a successful on-chip worker state into a per-run file under
    BENCH_STATE_DIR, so future runs that lose the tunnel can report the
    best RECORDED on-chip result instead of only the CPU fallback.
    Called on every merge; atomic overwrite of this run's own file."""
    tpu = read_state(tpu_path)
    if not tpu or tpu.get("value", 0) <= 0:
        return
    if tpu.get("extras", {}).get("platform") != "tpu":
        return  # a worker that silently fell back to CPU is not on-chip
    dest = os.path.join(STATE_DIR, f"tpu_run_{run_tag}.json")
    tmp = dest + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(tpu, f)
        os.replace(tmp, dest)
    except OSError:
        pass


def merge(state: BenchState, cpu_path: str, tpu_path: str,
          run_tag: str = "current") -> None:
    """Fold worker files into the orchestrator's result. TPU wins the
    headline the moment it has a nonzero value; CPU is insurance."""
    tpu = read_state(tpu_path)
    cpu = read_state(cpu_path)
    persist_tpu_run(tpu_path, run_tag)
    chosen, source = None, None
    if tpu and tpu.get("value", 0) > 0:
        chosen, source = tpu, "tpu_worker"
    elif cpu and cpu.get("value", 0) > 0:
        chosen, source = cpu, "cpu_worker"
    elif tpu and tpu.get("extras", {}).get("stages_completed"):
        chosen, source = tpu, "tpu_worker"
    elif cpu:
        chosen, source = cpu, "cpu_worker"
    with state.lock:
        probes = {k: v for k, v in state.result["extras"].items()
                  if k.startswith(("tpu_probe", "tpu_worker", "tpu_launches",
                                   "cpu_worker", "orchestrator"))}
        if chosen is not None:
            state.result["value"] = chosen["value"]
            state.result["vs_baseline"] = chosen["vs_baseline"]
            state.result["extras"] = dict(chosen.get("extras", {}))
            state.result["extras"]["headline_source"] = source
        state.result["extras"].update(probes)
        # Carry the non-headline worker's key numbers for the record.
        other = cpu if source == "tpu_worker" else tpu
        other_name = "cpu_worker" if source == "tpu_worker" else "tpu_worker"
        if other:
            state.result["extras"][other_name] = {
                "value": other.get("value", 0),
                "platform": other.get("extras", {}).get("platform"),
                "stages_completed": other.get("extras", {}).get(
                    "stages_completed", []),
            }
        if chosen is None:
            # Nothing measured at all yet — still say so explicitly; a
            # reader of the official JSON must never have to infer where
            # the headline came from.
            state.result["extras"]["headline_source"] = "none"
        if source != "tpu_worker":
            # The headline stays whatever THIS run measured — but when
            # the tunnel is down for the whole run (probe timeout), point
            # the record at the best RECORDED on-chip result — persisted
            # bench_state runs and checked-in artifacts — so a reader of
            # the official JSON can find the chip capability evidence.
            best = best_recorded_tpu_artifact()
            if best is not None:
                state.result["extras"]["best_recorded_tpu_artifact"] = best
    state.flush()


def best_recorded_tpu_artifact():
    """Scan checked-in bench artifacts AND persisted bench_state runs
    (``tpu_run_*.json``, written by :func:`persist_tpu_run` on every
    successful on-chip run) for the highest on-chip headline (clearly
    labeled as a PRIOR run — never substituted for the measured
    value)."""
    import glob
    import json as _json

    art_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "artifacts")
    best = None
    candidates = (glob.glob(os.path.join(art_dir, "bench_r*_try*.json"))
                  + glob.glob(os.path.join(STATE_DIR, "tpu_run_*.json")))
    for path in candidates:
        try:
            with open(path) as f:
                data = _json.load(f)
        except (OSError, ValueError):
            continue
        if (data.get("extras", {}).get("platform") == "tpu"
                and data.get("value", 0) > (best or {}).get("value", 0)):
            best = {"file": os.path.relpath(path, art_dir),
                    "value": data["value"],
                    "vs_baseline": data.get("vs_baseline"),
                    "note": "prior on-chip run recorded in artifacts/ or "
                            "bench_state/; this run's headline above was "
                            "measured without the chip"}
    return best


def main() -> None:
    os.makedirs(STATE_DIR, exist_ok=True)
    cpu_path = os.path.join(STATE_DIR, "cpu.json")
    tpu_path = os.path.join(STATE_DIR, "tpu.json")
    for p in (cpu_path, tpu_path):
        try:
            os.remove(p)
        except OSError:
            pass

    state = BenchState(os.path.join(STATE_DIR, "merged.json"))
    # One persisted tpu_run_<tag>.json per orchestrator run: every merge
    # overwrites this run's own file, never a prior run's record.
    run_tag = time.strftime("%Y%m%d_%H%M%S")

    def watchdog() -> None:
        while remaining() > 0:
            if state.emitted:
                return
            time.sleep(min(1.0, max(remaining(), 0.01)))
        merge(state, cpu_path, tpu_path, run_tag)
        state.record(orchestrator_watchdog_fired=True)
        state.emit()
        os._exit(0)

    threading.Thread(target=watchdog, daemon=True,
                     name="bench-watchdog").start()

    try:
        # CPU insurance starts immediately: small shapes, finishes well
        # inside its slice, guarantees a nonzero artifact if the chip
        # never shows up.
        cpu_budget = min(BUDGET_S * 0.5, 110.0)
        cpu_proc = launch_worker("cpu", cpu_path, cpu_budget)
        state.record(cpu_worker_budget_s=round(cpu_budget, 1))

        # Probe loop: retry with backoff for as long as a TPU worker
        # could still do useful work (it needs ~60 s minimum: scorer
        # stage + one compile + a few step windows).
        tpu_proc = None
        probes = 0
        tpu_launches = 0
        while remaining() > 55.0:
            if tpu_proc is None:
                probes += 1
                if probe_tpu(state, min(PROBE_TIMEOUT_S,
                                        remaining() - 40.0)):
                    tpu_budget = remaining() - 12.0
                    tpu_proc = launch_worker("tpu", tpu_path, tpu_budget)
                    tpu_launches += 1
                    state.record(tpu_worker_budget_s=round(tpu_budget, 1),
                                 tpu_launches=tpu_launches)
                else:
                    time.sleep(min(5.0, max(remaining() - 50.0, 0.5)))
                    continue
            rc = tpu_proc.poll()
            snap = read_state(tpu_path)
            tpu_value = (snap or {}).get("value", 0)
            if tpu_value > 0 and cpu_proc.poll() is None:
                # Insurance no longer needed; stop contending for host
                # cores the TPU input pipeline wants.
                cpu_proc.terminate()
                state.record(cpu_worker_terminated_early=True)
            if rc is None:
                time.sleep(1.0)
                continue
            # TPU worker exited. Done if it produced the goods;
            # otherwise (tunnel died mid-run) re-probe and relaunch
            # with whatever budget is left.
            done = bool((snap or {}).get("extras", {}).get("worker_done"))
            if done or tpu_value > 0:
                break
            state.record(tpu_worker_rc=rc)
            tpu_proc = None

        state.record(tpu_probe_count=probes)

        # A live TPU worker runs to its granted budget (only the emit
        # margin is reserved) — the probe loop above exits early because
        # RELAUNCHING needs ≥55 s to be useful, not because a worker
        # already mid-measurement should die.
        while (tpu_proc is not None and tpu_proc.poll() is None
               and remaining() > 10.0):
            snap = read_state(tpu_path)
            if ((snap or {}).get("value", 0) > 0
                    and cpu_proc.poll() is None):
                cpu_proc.terminate()
                state.record(cpu_worker_terminated_early=True)
            time.sleep(1.0)

        # If no TPU result, give the CPU worker its remaining slice.
        snap = read_state(tpu_path)
        if not (snap and snap.get("value", 0) > 0):
            while cpu_proc.poll() is None and remaining() > 8.0:
                time.sleep(0.5)
        for proc in (cpu_proc, tpu_proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
        merge(state, cpu_path, tpu_path, run_tag)
    finally:
        merge(state, cpu_path, tpu_path, run_tag)
        state.emit()


def single_stage_main(name: str, stage_opts: dict | None = None) -> None:
    """`bench.py <stage>`: run ONE registry stage on the CPU platform
    with the full budget and print its extras as the JSON line — the
    entry the driver (and a human) uses to gate a single ladder, e.g.
    `bench.py chaos` or `bench.py scheduler --rungs 100,1000`."""
    state = BenchState(os.path.join(STATE_DIR, f"stage_{name}.json"))
    os.makedirs(STATE_DIR, exist_ok=True)
    run_stages(state, "cpu", BUDGET_S, only=name, stage_opts=stage_opts)
    state.emit()


def parse_stage_opts(argv: list) -> dict:
    """Per-stage CLI options after the stage name. ``--rungs 100,1000``
    trims the scheduler's in-process ladder without editing source (the
    dev-box path); ``--cluster-peers N`` pins the cluster-rung swarm
    size (0 skips the cluster ladder)."""
    opts: dict = {}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "--rungs" and i + 1 < len(argv):
            # Sorted + deduped: the ladder verdict compares LAST rung
            # against FIRST — a descending list would invert the ratio
            # and trivially green-light a contention regression.
            opts["rungs"] = sorted(
                {int(s) for s in argv[i + 1].split(",") if s})
            i += 2
        elif arg == "--cluster-peers" and i + 1 < len(argv):
            opts["cluster_peers"] = int(argv[i + 1])
            i += 2
        else:
            raise SystemExit(f"unknown stage option {arg!r} "
                             "(have: --rungs N,N,..., --cluster-peers N)")
    return opts


def check_regression_main(stage_name: str) -> None:
    """`bench.py <stage> --check-regression` — the one-command perf/
    robustness gates: a fresh run vs the best persisted
    artifacts/bench_state record, exiting non-zero on regression.

    - ``dataplane``: fresh upload-loopback rung vs the best recorded
      MB/s (docs/DATAPLANE.md fraction), PLUS a fresh download density
      rung + async-engine loopback + native splice rung — fails on a
      download thread-census breach at any rung, a density aggregate
      under 0.5× the best record, a single-task loopback under 0.7×
      the recorded MB/s, or a splice loopback under 0.5× the recorded
      splice MB/s.
    - ``chaos``: fresh fault ladder + daemon-kill rung vs the best
      recorded chaos run (docs/CHAOS.md) — any lost verdict or a
      goodput-retention collapse fails the gate.
    - ``fanout``: fresh dissemination ladder vs the best recorded
      fanout run (docs/FANOUT.md) — a lost verdict or a 2× TTLB /
      amplification collapse fails the gate.
    - ``scheduler``: fresh top-rung swarm run vs the best recorded
      scheduler run (docs/SCHEDULER.md) — under 0.5× the recorded
      decisions/sec or over 2× the recorded announce p99 fails.
    - ``mlguard``: a fresh poisoned-model rung must hold its absolute
      bounds (gate rejection, 100 % success, rollback ≤ 2 ×
      reload_interval, quality floor — docs/CHAOS.md); the best
      record rides along for trend reading.
    - ``replay``: a fresh record→gate→A/B pass must hold its absolute
      bounds (bit-identical determinism, both models gate-promoted,
      ML/learned-cost regret within the documented delta of the rule
      baseline, recorder overhead ≤ 5% — docs/REPLAY.md), PLUS a
      fresh vectorized throughput-ladder rung with bit-identical
      digests and vectorized decisions/sec ≥ 0.33× the best persisted
      replay_ladder_run record.
    - ``obs``: a fresh observability stage must hold its absolute
      bounds (disrupted task tail-captured end to end, analyzer blames
      the injected stall, every stats block scrapeable, tracing
      overhead ≤ 1.05× on announce p99 and loopback MB/s —
      docs/OBSERVABILITY.md).
    - ``qos``: a fresh mixed-workload + flooding-tenant stage must
      hold its absolute bounds (interactive p99 within bound in both
      rungs, bulk ≥ 70% of its alone throughput, sheds only on the
      flooding class — docs/QOS.md).
    - ``geo``: fresh multi-site ladder vs the best recorded geo run
      (docs/GEO.md) — a lost verdict (including the site-partition
      rung) or a 2× TTLB / WAN-amplification collapse fails the
      gate.
    - ``federated``: a fresh clean + poisoned federated pass (kill
      rung skipped — subprocess cold starts don't belong in a quick
      gate) must hold its absolute bounds (screens catch both the
      flipped/scaled and NaN clusters, gate-promoted global
      matches-or-beats the best solo regret, poisoned regret within
      1.2× clean — docs/FEDERATED.md); the best record rides along
      for trend reading."""
    if stage_name == "dataplane":
        from dragonfly2_tpu.client.dataplane import (
            check_download_regression,
        )
        from dragonfly2_tpu.client.uploadbench import check_regression

        upload = check_regression(STATE_DIR)
        download = check_download_regression(STATE_DIR)
        result = {"upload": upload, "download": download,
                  "passed": bool(upload["passed"] and download["passed"])}
    elif stage_name == "chaos":
        from dragonfly2_tpu.client.chaosbench import check_chaos_regression

        result = check_chaos_regression(STATE_DIR)
    elif stage_name == "fanout":
        from dragonfly2_tpu.client.fanoutbench import check_fanout_regression

        result = check_fanout_regression(STATE_DIR)
    elif stage_name == "scheduler":
        from dragonfly2_tpu.scheduler.loadbench import (
            check_scheduler_regression,
        )

        result = check_scheduler_regression(STATE_DIR)
    elif stage_name == "mlguard":
        from dragonfly2_tpu.inference.guardbench import (
            check_mlguard_regression,
        )

        result = check_mlguard_regression(STATE_DIR)
    elif stage_name == "replay":
        from dragonfly2_tpu.scheduler.replaybench import (
            check_replay_regression,
        )

        result = check_replay_regression(STATE_DIR)
    elif stage_name == "obs":
        from dragonfly2_tpu.client.obsbench import check_obs_regression

        result = check_obs_regression(STATE_DIR)
    elif stage_name == "qos":
        from dragonfly2_tpu.client.qosbench import check_qos_regression

        result = check_qos_regression(STATE_DIR)
    elif stage_name == "geo":
        from dragonfly2_tpu.client.geobench import check_geo_regression

        result = check_geo_regression(STATE_DIR)
    elif stage_name == "federated":
        from dragonfly2_tpu.train.fedbench import (
            check_federated_regression,
        )

        result = check_federated_regression(STATE_DIR)
    else:
        raise SystemExit(
            f"no regression gate for stage {stage_name!r} "
            "(have: dataplane, chaos, fanout, scheduler, mlguard, "
            "replay, obs, qos, geo, federated)")
    print(json.dumps(result), flush=True)
    sys.exit(0 if result["passed"] else 1)


if __name__ == "__main__":
    if len(sys.argv) >= 5 and sys.argv[1] == "--worker":
        worker_main(sys.argv[2], sys.argv[3], float(sys.argv[4]))
    elif (len(sys.argv) == 3
          and sys.argv[2] == "--check-regression"):
        check_regression_main(sys.argv[1])
    elif len(sys.argv) >= 2 and not sys.argv[1].startswith("-"):
        single_stage_main(sys.argv[1], parse_stage_opts(sys.argv[2:]))
    else:
        main()
