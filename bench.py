"""Benchmark entry point — prints ONE JSON line for the driver, always.

Headline metric (BASELINE.json north star): GraphSAGE topology-model
training throughput in samples(edges)/sec/chip, steady-state (compile
excluded). Extras carry the second tracked number — scheduler
parent-selection p50 latency through the TPU-backed ML scorer (<1 ms
target) — plus MLP training stats and pipeline diagnostics.

Un-killability contract (the round-1 failure was a silent rc=124):
- TPU availability is probed in a SUBPROCESS with a hard timeout — a
  hanging backend init (observed: ``jax.devices()`` on this machine's
  ``axon`` platform can stall for minutes) falls back to CPU instead of
  stalling the bench, flagged as ``extras.platform: "cpu_fallback"``.
- Every stage is wall-clock budgeted (``max_seconds`` step loops measure
  throughput from steps actually run, not fixed epoch counts).
- A watchdog thread force-emits whatever has been measured and exits
  before the driver's kill; the JSON line is also emitted from a
  ``finally`` path on any exception.

``vs_baseline`` is measured/target against the self-established target
(the reference publishes no numbers and its training path is a stub; see
BASELINE.md): 100k samples/sec/chip for GraphSAGE training.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

TARGET_GNN_SAMPLES_PER_SEC_PER_CHIP = 100_000.0
TARGET_P50_MS = 1.0

# Total wall budget. The driver's observed kill horizon is >240 s; leave
# margin so the watchdog always wins the race against SIGKILL.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "200"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT_S", "60"))

_t0 = time.perf_counter()
# Reentrant: every mutation of ``result`` and the final dumps hold this
# lock, so the watchdog can never serialize a dict mid-mutation (which
# would raise inside json.dumps AFTER latching the emitted flag and lose
# the line forever).
_emit_lock = threading.RLock()
_emitted = False

result = {
    "metric": "graphsage_train_samples_per_sec_per_chip",
    "value": 0,
    "unit": "samples/sec/chip",
    "vs_baseline": 0.0,
    "extras": {"stages_completed": [], "platform": "unknown"},
}


def record(**extras) -> None:
    with _emit_lock:
        result["extras"].update(extras)


def stage_done(name: str) -> None:
    with _emit_lock:
        result["extras"]["stages_completed"].append(name)


def set_headline(value: float) -> None:
    with _emit_lock:
        result["value"] = int(value)
        result["vs_baseline"] = round(
            value / TARGET_GNN_SAMPLES_PER_SEC_PER_CHIP, 3)


def emit() -> None:
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        result["extras"]["wall_seconds"] = round(time.perf_counter() - _t0, 1)
        line = json.dumps(result)
        _emitted = True
        print(line, flush=True)


def remaining() -> float:
    return BUDGET_S - (time.perf_counter() - _t0)


def _watchdog() -> None:
    # Sleep in small slices so a fast successful run exits normally.
    while remaining() > 0:
        if _emitted:
            return
        time.sleep(min(1.0, max(remaining(), 0.01)))
    stage_done("watchdog_fired")
    emit()
    os._exit(0)


def probe_tpu() -> bool:
    """Check — in a throwaway subprocess — that backend init completes.

    The subprocess inherits the environment (this machine's sitecustomize
    selects the TPU platform); if it can't enumerate an accelerator
    within the timeout, the main process must not try.
    """
    code = ("import jax; ds = jax.devices(); "
            "print(ds[0].platform, len(ds))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True,
            timeout=min(PROBE_TIMEOUT_S, max(remaining() - 90, 5)),
        )
    except subprocess.TimeoutExpired:
        record(tpu_probe="timeout")
        return False
    if proc.returncode != 0:
        record(tpu_probe=f"rc={proc.returncode}")
        return False
    out = proc.stdout.strip().split()
    record(tpu_probe=" ".join(out))
    return bool(out) and out[0] not in ("cpu",)


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True, name="bench-watchdog").start()
    try:
        run_stages()
    finally:
        emit()


def run_stages() -> None:
    probe_t0 = time.perf_counter()
    on_tpu = probe_tpu()
    record(tpu_probe_seconds=round(time.perf_counter() - probe_t0, 1))
    if not on_tpu:
        # Must happen before ANY backend use; the env var alone is
        # overridden by this machine's sitecustomize.
        import jax

        jax.config.update("jax_platforms", "cpu")
        record(platform="cpu_fallback")
    import jax

    from dragonfly2_tpu.data import SyntheticCluster
    from dragonfly2_tpu.parallel import data_parallel_mesh
    from dragonfly2_tpu.train import (
        GNNTrainConfig,
        MLPTrainConfig,
        train_gnn,
        train_mlp,
    )

    mesh = data_parallel_mesh()
    if on_tpu:
        record(platform=jax.devices()[0].platform)
    record(n_devices=mesh.n_data)
    stage_done("init")

    cluster = SyntheticCluster(n_hosts=2000, seed=0)

    # Stage 1 (headline): GraphSAGE on a 2M-edge probe graph, step loop
    # time-boxed to ~half the remaining budget; throughput = steps
    # actually completed after the compiled first step.
    graph = cluster.probe_graph(2_000_000)
    gnn_budget = max(min(remaining() * 0.45, 75.0), 5.0)
    gnn = train_gnn(
        graph,
        GNNTrainConfig(batch_size=8192, epochs=1000, eval_fraction=0.02,
                       max_seconds=gnn_budget),
        mesh,
    )
    per_chip = gnn.samples_per_sec / mesh.n_data
    set_headline(per_chip)
    record(
        gnn_f1=round(gnn.f1, 4),
        gnn_precision=round(gnn.precision, 4),
        gnn_recall=round(gnn.recall, 4),
        gnn_steps=gnn.steps,
        gnn_compile_seconds=round(gnn.compile_seconds, 1),
        gnn_step_seconds_budget=round(gnn_budget, 1),
    )
    stage_done("gnn")

    # Stage 2: parent-selection latency through the jitted scorer. Uses a
    # quickly-trained MLP (latency is weight-independent, but train a real
    # one so mae is reportable).
    X, y = cluster.pair_example_columns(300_000)
    mlp = train_mlp(
        X, y,
        MLPTrainConfig(epochs=100, batch_size=16384,
                       max_seconds=max(min(remaining() * 0.4, 30.0), 2.0)),
        mesh,
    )
    record(
        mlp_train_samples_per_sec_per_chip=int(
            mlp.samples_per_sec / mesh.n_data),
        mlp_eval_mae_mbps=round(mlp.mae, 3),
    )
    stage_done("mlp")

    from dragonfly2_tpu.inference import ParentScorer

    scorer = ParentScorer(mlp.model, mlp.params, mlp.normalizer,
                          mlp.target_norm)
    iters = 500 if remaining() > 30 else 100
    latency = scorer.benchmark(batch=16, iters=iters)
    record(
        parent_select_p50_ms=round(latency["p50_ms"], 4),
        parent_select_p99_ms=round(latency["p99_ms"], 4),
        parent_select_vs_1ms_target=round(
            TARGET_P50_MS / max(latency["p50_ms"], 1e-9), 3),
    )
    stage_done("scorer")


if __name__ == "__main__":
    main()
