"""Benchmark entry point — prints ONE JSON line for the driver, always.

Headline metric (BASELINE.json north star): GraphSAGE topology-model
training throughput in samples(edges)/sec/chip, steady-state (compile
excluded). Extras carry the second tracked number — scheduler
parent-selection p50 latency through the TPU-backed ML scorer (<1 ms
target) — plus MLP training stats and pipeline diagnostics.

Round-3 accounting rules (the round-2 failure was value=0 with the number
existing — watchdog fired before train_gnn returned and nothing had
published partial throughput):
- The scorer p50 stage runs FIRST (latency is weight-independent — a
  synthetically initialized MLP measures the same dispatch path), so the
  <1 ms target is validated before the GNN stage can starve it.
- The GNN trainer publishes throughput incrementally (StepBudget
  on_progress → set_headline every ~10 steps) so a watchdog fire emits
  the latest steady-state rate, never zero.
- Budgets are per-STAGE: the GNN step loop gets what remains after
  observed init/compile costs, and the eval pass has its own wall cap.
- A persistent XLA compilation cache (utils/compilecache.py) amortizes
  the ~25 s train-step compile across runs.
- Sub-stage timestamps (t_*) are recorded as they happen so a watchdog
  fire is diagnosable from the JSON alone.

Un-killability contract (the round-1 failure was a silent rc=124):
- TPU availability is probed in a SUBPROCESS with a hard timeout; a
  hanging backend init falls back to CPU, flagged in extras.
- A watchdog thread force-emits whatever has been measured and exits
  before the driver's kill; the JSON line is also emitted from a
  ``finally`` path on any exception.

``vs_baseline`` is measured/target against the self-established target
(the reference publishes no numbers and its training path is a stub; see
BASELINE.md): 100k samples/sec/chip for GraphSAGE training.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

TARGET_GNN_SAMPLES_PER_SEC_PER_CHIP = 100_000.0
TARGET_P50_MS = 1.0

# Total wall budget. The driver's observed kill horizon is >240 s; leave
# margin so the watchdog always wins the race against SIGKILL.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "200"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT_S", "60"))

_t0 = time.perf_counter()
# Reentrant: every mutation of ``result`` and the final dumps hold this
# lock, so the watchdog can never serialize a dict mid-mutation (which
# would raise inside json.dumps AFTER latching the emitted flag and lose
# the line forever).
_emit_lock = threading.RLock()
_emitted = False

result = {
    "metric": "graphsage_train_samples_per_sec_per_chip",
    "value": 0,
    "unit": "samples/sec/chip",
    "vs_baseline": 0.0,
    "extras": {"stages_completed": [], "platform": "unknown"},
}


def record(**extras) -> None:
    with _emit_lock:
        result["extras"].update(extras)


def stamp(name: str) -> None:
    """Record a sub-stage timestamp (seconds since process start)."""
    record(**{f"t_{name}": round(time.perf_counter() - _t0, 1)})


def stage_done(name: str) -> None:
    with _emit_lock:
        result["extras"]["stages_completed"].append(name)
    stamp(name)


def set_headline(value: float) -> None:
    with _emit_lock:
        result["value"] = int(value)
        result["vs_baseline"] = round(
            value / TARGET_GNN_SAMPLES_PER_SEC_PER_CHIP, 3)


def emit() -> None:
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        result["extras"]["wall_seconds"] = round(time.perf_counter() - _t0, 1)
        line = json.dumps(result)
        _emitted = True
        print(line, flush=True)


def remaining() -> float:
    return BUDGET_S - (time.perf_counter() - _t0)


def _watchdog() -> None:
    # Sleep in small slices so a fast successful run exits normally.
    while remaining() > 0:
        if _emitted:
            return
        time.sleep(min(1.0, max(remaining(), 0.01)))
    stage_done("watchdog_fired")
    emit()
    os._exit(0)


def probe_tpu() -> bool:
    """Check — in a throwaway subprocess — that backend init completes.

    The subprocess inherits the environment (this machine's sitecustomize
    selects the TPU platform); if it can't enumerate an accelerator
    within the timeout, the main process must not try.
    """
    code = ("import jax; ds = jax.devices(); "
            "print(ds[0].platform, len(ds))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True,
            timeout=min(PROBE_TIMEOUT_S, max(remaining() - 90, 5)),
        )
    except subprocess.TimeoutExpired:
        record(tpu_probe="timeout")
        return False
    if proc.returncode != 0:
        record(tpu_probe=f"rc={proc.returncode}")
        return False
    out = proc.stdout.strip().split()
    record(tpu_probe=" ".join(out))
    return bool(out) and out[0] not in ("cpu",)


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True, name="bench-watchdog").start()
    try:
        run_stages()
    finally:
        emit()


def run_stages() -> None:
    probe_t0 = time.perf_counter()
    on_tpu = probe_tpu()
    record(tpu_probe_seconds=round(time.perf_counter() - probe_t0, 1))
    if not on_tpu:
        # Must happen before ANY backend use; the env var alone is
        # overridden by this machine's sitecustomize.
        import jax

        jax.config.update("jax_platforms", "cpu")
        record(platform="cpu_fallback")

    from dragonfly2_tpu.utils.compilecache import enable_compilation_cache

    record(compile_cache_dir=enable_compilation_cache())

    import jax

    from dragonfly2_tpu.data import SyntheticCluster
    from dragonfly2_tpu.parallel import data_parallel_mesh
    from dragonfly2_tpu.train import GNNTrainConfig, train_gnn

    mesh = data_parallel_mesh()
    if on_tpu:
        record(platform=jax.devices()[0].platform)
    record(n_devices=mesh.n_data)
    stage_done("init")

    # Stage 1: parent-selection p50 through the jitted scorer, FIRST —
    # latency is weight-independent, so a synthetically initialized MLP
    # measures the same compiled dispatch path a trained one would, and
    # the <1 ms target gets validated before the GNN stage can starve it.
    # The stage is wall-capped (a degraded tunnel must not eat the GNN
    # budget), and the raw number is decomposed: a no-op jit call
    # measures the platform dispatch floor (the tunneled axon TPU pays a
    # network round trip per blocking call — observed ~68 ms even for
    # the "cpu" device, the whole backend is remote), and
    # parent_select_model_ms reports p50 minus that floor — an estimate
    # of what a scheduler colocated with its TPU sidecar would observe.
    import jax.numpy as jnp

    from dragonfly2_tpu.inference import ParentScorer
    from dragonfly2_tpu.models.mlp import MLPBandwidthPredictor, Normalizer
    from dragonfly2_tpu.scheduler.evaluator.scoring import FEATURE_DIM

    scorer_budget = max(min(remaining() * 0.15, 20.0), 3.0)
    scorer_t0 = time.perf_counter()

    mlp_model = MLPBandwidthPredictor()
    mlp_params = mlp_model.init(jax.random.key(0),
                                jnp.zeros((1, FEATURE_DIM)))
    scorer = ParentScorer(mlp_model, mlp_params,
                          Normalizer.identity(FEATURE_DIM),
                          Normalizer.identity(1), max_batch=16)

    # Dispatch floor: p50 of a blocking no-op jit round trip. On the
    # tunneled axon platform this IS the p50 (observed ~68 ms RTT even
    # for the "cpu" device — the whole backend is remote); the
    # hardware-independent model cost is p50 - floor.
    noop = jax.jit(lambda x: x + 1)
    x0 = jnp.zeros(8)
    noop(x0).block_until_ready()
    floor = []
    for _ in range(15):
        t = time.perf_counter()
        noop(x0).block_until_ready()
        floor.append((time.perf_counter() - t) * 1e3)
    floor_p50 = sorted(floor)[len(floor) // 2]
    record(dispatch_floor_p50_ms=round(floor_p50, 4))

    # Adaptive iteration count: probe, then fill the stage's remaining
    # wall budget (never fewer than 20, never more than 300 iters).
    probe = scorer.benchmark(batch=16, iters=10)
    stage_left = scorer_budget - (time.perf_counter() - scorer_t0)
    iters = int(max(20, min(300, stage_left * 1e3 / max(probe["p50_ms"], 1e-3))))
    latency = scorer.benchmark(batch=16, iters=iters)
    record(
        parent_select_p50_ms=round(latency["p50_ms"], 4),
        parent_select_p99_ms=round(latency["p99_ms"], 4),
        parent_select_iters=iters,
        # Model-only cost with the platform round trip subtracted — what a
        # scheduler colocated with its TPU sidecar would observe.
        parent_select_model_ms=round(
            max(latency["p50_ms"] - floor_p50, 0.0), 4),
        parent_select_vs_1ms_target=round(
            TARGET_P50_MS / max(latency["p50_ms"], 1e-9), 3),
    )
    stage_done("scorer")

    # Stage 2 (headline): GraphSAGE on a 2M-edge probe graph. The step
    # loop gets the remaining budget minus reserves for eval + emit, and
    # publishes throughput incrementally so the watchdog always has the
    # latest steady-state rate. The CPU fallback (tunnel outage) shrinks
    # the problem so every stage COMPLETES — a small honest number
    # beats a watchdog kill mid-compile.
    if on_tpu:
        n_edges, batch, steps_per_call = 2_000_000, 8192, 8
    else:
        n_edges, batch, steps_per_call = 200_000, 2048, 1
    cluster = SyntheticCluster(n_hosts=2000, seed=0)
    graph = cluster.probe_graph(n_edges)
    stamp("graph_built")

    def on_progress(steps: int, rate: float) -> None:
        set_headline(rate / mesh.n_data)
        record(gnn_steps=steps)

    def on_compile(seconds: float) -> None:
        record(gnn_compile_seconds=round(seconds, 1))
        stamp("gnn_compile_done")

    # Reserves: the eval pass compiles its own (second) program on a cold
    # cache, so its cap is kept under the reserve and the emit margin is
    # generous — a watchdog fire mid-eval still emits the incrementally
    # published headline; only f1 would be lost.
    eval_reserve = max(min(remaining() * 0.2, 30.0), 5.0)
    emit_reserve = 15.0
    compile_reserve = 30.0  # uncached train-step compile; ~0 when cache hits
    gnn_budget = max(
        remaining() - eval_reserve - emit_reserve - compile_reserve, 5.0)
    record(gnn_step_seconds_budget=round(gnn_budget, 1))
    gnn = train_gnn(
        graph,
        # steps_per_call=8 on the chip: eight optimizer updates per
        # dispatch under lax.scan — the tunneled chip's per-dispatch
        # round trip bounds throughput, so amortizing it is the cheapest
        # 'more samples/sec' there is.
        GNNTrainConfig(batch_size=batch, epochs=1000, eval_fraction=0.02,
                       max_seconds=gnn_budget,
                       steps_per_call=steps_per_call,
                       progress_callback=on_progress,
                       compile_callback=on_compile,
                       eval_max_seconds=min(eval_reserve, 25.0)),
        mesh,
    )
    per_chip = gnn.samples_per_sec / mesh.n_data
    set_headline(per_chip)
    record(
        gnn_f1=round(gnn.f1, 4),
        gnn_precision=round(gnn.precision, 4),
        gnn_recall=round(gnn.recall, 4),
        gnn_steps=gnn.steps,
        gnn_compile_seconds=round(gnn.compile_seconds, 1),
    )
    stage_done("gnn")

    # Stage 3 (only if budget allows): MLP training throughput + honest
    # registry mae from a really-trained model. Needs headroom for its
    # own two compiles (train + eval) on a cold cache, so the entry bar
    # is high and the step budget leaves the emit margin alone.
    if remaining() > 45.0:
        from dragonfly2_tpu.train import MLPTrainConfig, train_mlp

        X, y = cluster.pair_example_columns(300_000)
        mlp = train_mlp(
            X, y,
            MLPTrainConfig(epochs=100, batch_size=16384,
                           max_seconds=max(
                               min(remaining() - 30.0, 25.0), 2.0),
                           progress_callback=lambda s, r: record(
                               mlp_train_samples_per_sec_per_chip=int(
                                   r / mesh.n_data)),
                           compile_callback=lambda c: record(
                               mlp_compile_seconds=round(c, 1))),
            mesh,
        )
        record(
            mlp_train_samples_per_sec_per_chip=int(
                mlp.samples_per_sec / mesh.n_data),
            mlp_eval_mae_mbps=round(mlp.mae, 3),
        )
        stage_done("mlp")


if __name__ == "__main__":
    main()
