"""Chip smoke tier: one of everything that only real hardware can break.

Run: ``python -m pytest tests_tpu -m tpu -q`` (manually / with a timeout;
the default suite never touches the chip — tests/conftest.py pins the
virtual CPU mesh). Budget: <5 minutes with a warm compile cache.
"""

from __future__ import annotations

import numpy as np
import pytest


class TestChipBasics:
    def test_device_is_accelerator(self, tpu_device):
        assert tpu_device.platform != "cpu"

    def test_matmul_bf16_on_chip(self, tpu_device):
        import jax
        import jax.numpy as jnp

        a = jnp.ones((256, 256), jnp.bfloat16)
        out = jax.jit(lambda x: (x @ x).sum())(a)
        assert float(out) == pytest.approx(256.0 ** 3, rel=1e-2)


class TestTrainSmoke:
    def test_gnn_one_epoch_fused(self, tpu_device):
        from dragonfly2_tpu.data import SyntheticCluster
        from dragonfly2_tpu.parallel import data_parallel_mesh
        from dragonfly2_tpu.train import GNNTrainConfig, train_gnn

        graph = SyntheticCluster(n_hosts=100, seed=0).probe_graph(10000)
        res = train_gnn(
            graph,
            GNNTrainConfig(hidden=32, embed=16, batch_size=512, epochs=1,
                           eval_fraction=0.1),
            data_parallel_mesh(),
        )
        assert res.steps >= 1
        assert np.isfinite(res.history[-1])
        assert 0.0 <= res.f1 <= 1.0

    def test_gnn_multi_step_scan(self, tpu_device):
        """steps_per_call>1 on the real chip: the scan program compiles
        and the dispatch-amortized path learns."""
        from dragonfly2_tpu.data import SyntheticCluster
        from dragonfly2_tpu.parallel import data_parallel_mesh
        from dragonfly2_tpu.train import GNNTrainConfig, train_gnn

        graph = SyntheticCluster(n_hosts=100, seed=0).probe_graph(10000)
        res = train_gnn(
            graph,
            GNNTrainConfig(hidden=32, embed=16, batch_size=512, epochs=2,
                           steps_per_call=4, eval_max_seconds=0.0),
            data_parallel_mesh(),
        )
        assert res.steps >= 1
        assert np.isfinite(res.history[-1])
        assert res.samples_per_sec > 0

    def test_mlp_one_epoch(self, tpu_device):
        from dragonfly2_tpu.data import SyntheticCluster
        from dragonfly2_tpu.parallel import data_parallel_mesh
        from dragonfly2_tpu.train import MLPTrainConfig, train_mlp

        X, y = SyntheticCluster(n_hosts=50, seed=0).pair_example_columns(4096)
        res = train_mlp(
            X, y, MLPTrainConfig(hidden=(32,), epochs=1, batch_size=1024),
            data_parallel_mesh(),
        )
        assert res.history and np.isfinite(res.history[-1])
        assert res.samples_per_sec > 0


class TestScorerSmoke:
    def test_scorer_call_and_floor(self, tpu_device):
        """One scorer call end to end + the dispatch floor, so latency
        regressions on the chip path are visible outside bench."""
        import jax
        import jax.numpy as jnp

        from dragonfly2_tpu.inference import ParentScorer
        from dragonfly2_tpu.models.mlp import MLPBandwidthPredictor, Normalizer
        from dragonfly2_tpu.scheduler.evaluator.scoring import FEATURE_DIM

        model = MLPBandwidthPredictor(hidden=(32,))
        params = model.init(jax.random.key(0), jnp.zeros((1, FEATURE_DIM)))
        scorer = ParentScorer(model, params,
                              Normalizer.identity(FEATURE_DIM),
                              Normalizer.identity(1), max_batch=16)
        scores = scorer.score(
            np.random.default_rng(0).uniform(
                0, 1, (5, FEATURE_DIM)).astype(np.float32))
        assert scores.shape == (5,)
        assert np.all(np.isfinite(scores))
        lat = scorer.benchmark(batch=16, iters=20)
        assert lat["p50_ms"] > 0


class TestHBMSinkSmoke:
    def test_safetensors_pieces_to_device(self, tpu_device, tmp_path):
        """Config #5 path: unordered pieces → staging → device_put lands
        real arrays in device memory."""
        from dragonfly2_tpu.client.hbm_sink import HBMSink, write_safetensors

        rng = np.random.default_rng(1)
        tensors = {
            "w": rng.normal(size=(64, 32)).astype(np.float32),
            "b": rng.normal(size=(32,)).astype(np.float32),
        }
        path = str(tmp_path / "m.safetensors")
        write_safetensors(path, tensors)
        blob = open(path, "rb").read()
        sink = HBMSink(len(blob), device=tpu_device)
        piece = 4096
        offsets = list(range(0, len(blob), piece))
        rng.shuffle(offsets)
        for off in offsets:
            sink.write(off, blob[off:off + piece])
        arrays = sink.wait(timeout=60)
        for name, want in tensors.items():
            got = np.asarray(arrays[name])
            np.testing.assert_array_equal(got, want)
            assert arrays[name].devices() == {tpu_device}
        sink.close()

    def test_gat_gather_attention_on_chip(self, tpu_device):
        """Round-4 GAT path: neighbor-gather attention (O(N·K)) must
        train on the real chip — gathers/scatters are the layout-
        sensitive ops a CPU mesh can't vouch for."""
        from dragonfly2_tpu.data import SyntheticCluster
        from dragonfly2_tpu.parallel import data_parallel_mesh
        from dragonfly2_tpu.train import GATTrainConfig, train_gat

        graph = SyntheticCluster(n_hosts=64, seed=0).probe_graph(6000)
        res = train_gat(
            graph,
            GATTrainConfig(hidden=32, embed=16, layers=1, heads=4,
                           epochs=2, edge_batch_size=512,
                           eval_fraction=0.1),
            data_parallel_mesh(),
        )
        assert np.isfinite(res.history[-1])
        assert res.samples_per_sec > 0

    def test_ring_attention_on_chip(self, tpu_device):
        """shard_map + ppermute on the real backend (degenerate 1-chip
        ring): the collective path must compile and run on axon."""
        import jax
        import numpy as np

        from dragonfly2_tpu.parallel import data_parallel_mesh, ring_attention

        mesh = data_parallel_mesh().mesh
        rng = np.random.default_rng(0)
        q, k, v = (rng.standard_normal((32, 2, 8)).astype(np.float32)
                   for _ in range(3))
        out = jax.jit(lambda *a: ring_attention(
            *a, mesh=mesh, causal=True))(q, k, v)
        assert np.isfinite(np.asarray(out)).all()

    def test_ulysses_attention_on_chip(self, tpu_device):
        """All-to-all sequence parallelism on the real backend
        (degenerate 1-chip exchange) — and on TPU the local attention
        IS the pallas flash kernel, so this exercises the production
        a2a + flash composition end to end."""
        import jax
        import numpy as np

        from dragonfly2_tpu.parallel import (
            data_parallel_mesh,
            ulysses_attention,
        )

        mesh = data_parallel_mesh().mesh
        rng = np.random.default_rng(0)
        q, k, v = (rng.standard_normal((256, 4, 128)).astype(np.float32)
                   for _ in range(3))
        out = jax.jit(lambda *a: ulysses_attention(
            *a, mesh=mesh, causal=True))(q, k, v)
        assert np.isfinite(np.asarray(out)).all()

    def test_pipeline_and_moe_on_chip(self, tpu_device):
        """The pipeline and expert layouts on the real backend
        (degenerate 1-stage/1-expert meshes): the ppermute/all_to_all
        collective programs must lower and run on axon."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from dragonfly2_tpu.parallel import (
            moe_apply,
            pipeline_apply,
            stack_stage_params,
        )

        n = jax.device_count()
        rng = np.random.default_rng(0)
        d = 8
        params = stack_stage_params([
            {"w": np.eye(d, dtype=np.float32)} for _ in range(n)])
        x = rng.standard_normal((4 * n, d)).astype(np.float32)

        mesh_s = jax.make_mesh((n,), ("stage",))
        out = pipeline_apply(lambda p, t: t @ p["w"], params, x,
                             mesh=mesh_s)
        np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5)

        mesh_e = jax.make_mesh((n,), ("expert",))
        gates = rng.standard_normal((4 * n, n)).astype(np.float32)
        out = moe_apply(lambda p, t: t @ p["w"], params, x, gates,
                        mesh=mesh_e, capacity_factor=float(n) * 4)
        probs = np.asarray(jax.nn.softmax(jnp.asarray(gates), axis=-1))
        top = probs[np.arange(len(gates)), gates.argmax(-1)]
        np.testing.assert_allclose(np.asarray(out), x * top[:, None],
                                   rtol=1e-4, atol=1e-5)

    def test_graph_flash_kernel_on_chip(self, tpu_device):
        """The graph-flash pallas kernel (blocks-mode inner loop on a
        single TPU device) must agree with gather-mode attention through
        the real Mosaic compiler — this is the production dispatch
        blocks_graph_attention takes on the bench/serving chip."""
        import numpy as np

        from dragonfly2_tpu.data import SyntheticCluster
        from dragonfly2_tpu.models.graph_transformer import (
            GraphTransformer,
            build_neighbor_lists,
            pad_graph_sparse,
        )

        graph = SyntheticCluster(n_hosts=64, seed=0).probe_graph(2000)
        nbr, val = build_neighbor_lists(
            graph.n_nodes, graph.edge_src, graph.edge_dst,
            graph.edge_rtt_ns)
        f, nb, vl, _ = pad_graph_sparse(graph.node_features, nbr, val, 8)

        def embed(attention):
            import jax

            model = GraphTransformer(hidden=32, embed=16, layers=1,
                                     heads=4, chunk=128,
                                     attention=attention)
            params = model.init(jax.random.key(0), f, nb, vl,
                                np.zeros(2, np.int32), np.zeros(2, np.int32))
            return np.asarray(model.apply(
                params, f, nb, vl,
                method=GraphTransformer.node_embeddings))

        # "blocks" on a single TPU device dispatches the pallas kernel.
        np.testing.assert_allclose(embed("gather"), embed("blocks"),
                                   rtol=6e-2, atol=6e-2)

    def test_table_gather_kernels_on_chip(self, tpu_device):
        """The VMEM-resident gather/scatter-add kernels through the real
        Mosaic compiler: exact vs table[idx] and vs XLA's scatter-add
        (f32 accumulation both sides)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from dragonfly2_tpu.ops.table_gather import (
            neighbor_gather_pallas, table_gather, table_scatter_add)

        rng = np.random.default_rng(2)
        n, d, m = 1024, 256, 4096
        t = jnp.asarray(rng.standard_normal((n, d)), jnp.bfloat16)
        idx = jnp.asarray(rng.integers(0, n, m), jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(table_gather(t, idx), np.float32),
            np.asarray(t, np.float32)[np.asarray(idx)])

        ct = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
        got = table_scatter_add(ct, idx, n)
        ref = jnp.zeros((n, d)).at[idx].add(ct)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

        ix2 = jnp.asarray(rng.integers(0, n, (64, 16)), jnp.int32)
        tf = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
        ga = jax.grad(lambda x: jnp.sum(
            jnp.sin(neighbor_gather_pallas(x, ix2))))(tf)
        gb = jax.grad(lambda x: jnp.sum(jnp.sin(x[ix2])))(tf)
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=1e-4, atol=1e-5)

    def test_flash_attention_kernel_on_chip(self, tpu_device):
        """The pallas kernel through the real Mosaic compiler. Tolerance
        covers MXU default-precision rounding vs the dense reference's
        different blocking (~4e-3 max observed)."""
        import numpy as np

        from dragonfly2_tpu.ops import flash_attention
        from dragonfly2_tpu.ops.flash_attention import _dense_reference

        rng = np.random.default_rng(0)
        t, h, d = 512, 4, 128
        q, k, v = (rng.standard_normal((t, h, d)).astype(np.float32)
                   for _ in range(3))
        for causal in (False, True):
            out = flash_attention(q, k, v, causal)
            ref = _dense_reference(q, k, v, causal, t)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-2, atol=1e-2)
