"""TPU smoke-tier harness (round-3 verdict weak item 5).

Unlike tests/conftest.py this does NOT pin jax_platforms=cpu — these tests
run on whatever accelerator the machine registers (the axon-tunneled TPU
here). Run manually with a timeout:

    python -m pytest tests_tpu -m tpu -q

Keep the tier under 5 minutes: one train step per model family, one scorer
call, one HBM device_put — enough that chip-only breakage (backend-init
pathologies, dtype/layout surprises, tunnel dispatch) surfaces outside
bench runs.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_collection_modifyitems(config, items):
    # Everything in this directory is implicitly tpu-marked.
    for item in items:
        item.add_marker(pytest.mark.tpu)


@pytest.fixture(scope="session")
def tpu_device():
    import jax

    from dragonfly2_tpu.utils.compilecache import enable_compilation_cache

    enable_compilation_cache()
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        pytest.skip("no accelerator registered; smoke tier needs the chip")
    return dev
