#!/usr/bin/env python
"""Mint deployment TLS material: a CA, server leaves for the scheduler
wire, and client leaves for mutual TLS.

Deployment counterpart of the reference's cert distribution
(deploy/helm chart TLS values; pkg/rpc/credential.go consumes the
material). Usage:

    python deploy/gen_certs.py --out certs/ \
        --server scheduler --server 127.0.0.1 --client daemon

Each ``--server NAME`` mints ``NAME.pem``/``NAME.key`` with a DNS or IP
SAN (auto-detected); each ``--client NAME`` mints a CLIENT_AUTH leaf.
The CA (``ca.pem``/``ca.key``) is created on first run and reused, so
re-running adds leaves without invalidating the fleet.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonfly2_tpu.utils.certs import CertAuthority  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("gen_certs")
    parser.add_argument("--out", default="certs",
                        help="directory for the CA and leaves")
    parser.add_argument("--server", action="append", default=[],
                        help="server SAN (DNS name or IP); repeatable")
    parser.add_argument("--client", action="append", default=[],
                        help="client identity for mutual TLS; repeatable")
    args = parser.parse_args(argv)

    ca = CertAuthority(args.out)
    print(f"CA: {ca.ca_cert_path}")
    for host in args.server or ["127.0.0.1"]:
        cert, key = ca.cert_for(host)
        # cert_for caches under hashed leaf names; copy to stable,
        # operator-friendly paths the compose file can mount.
        safe = host.replace(":", "_").replace("/", "_")
        dst_cert = os.path.join(args.out, f"{safe}.pem")
        dst_key = os.path.join(args.out, f"{safe}.key")
        if os.path.abspath(cert) != os.path.abspath(dst_cert):
            shutil.copyfile(cert, dst_cert)
            shutil.copyfile(key, dst_key)
        print(f"server {host}: {dst_cert}")
    for name in args.client:
        cert, key = ca.client_cert_for(name)
        print(f"client {name}: {cert}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
