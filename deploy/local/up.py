#!/usr/bin/env python
"""Localhost process supervisor — stand up the full topology with one
command, no container runtime required.

The plain-process twin of ``deploy/docker-compose.yaml`` (reference:
deploy/ helm charts + test/testdata/kind/config.yaml — the environment
its e2e tier runs against). Starts manager → scheduler (registered with
the manager, TLS-terminated wire when ``--tls``) → seed daemon → N peer
daemons (scheduler targets via manager **dynconfig**, not pinned), waits
for each to be ready, and writes ``state.json`` with every port and pid
so tests and operators can drive the mesh:

    python deploy/local/up.py up   --dir /tmp/df2 --tls --peers 2
    python deploy/local/up.py down --dir /tmp/df2

``df2-get`` against the deployed mesh (ports from state.json):

    python -m dragonfly2_tpu.cmd.dfget URL -O out \
        --daemon 127.0.0.1:<peer_rpc_port>
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_port(port: int, proc: subprocess.Popen, what: str,
              timeout: float = 60.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited rc={proc.returncode} during startup — "
                f"see its .err log")
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"{what}: port {port} never opened")


def spawn(run_dir: str, name: str, module: str, flags: list) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    out = open(os.path.join(run_dir, f"{name}.out"), "wb")
    err = open(os.path.join(run_dir, f"{name}.err"), "wb")
    return subprocess.Popen([sys.executable, "-m", module] + flags,
                            stdout=out, stderr=err, env=env, cwd=run_dir)


def cmd_up(args) -> int:
    run_dir = os.path.abspath(args.dir)
    os.makedirs(run_dir, exist_ok=True)
    state_path = os.path.join(run_dir, "state.json")
    if os.path.exists(state_path):
        print(f"{state_path} exists — run `down` first", file=sys.stderr)
        return 1

    ports = {
        "manager": free_port(), "manager_internal": free_port(),
        "scheduler": free_port(), "seed_rpc": free_port(),
        "seed_metrics": free_port(),
        "peer_rpc": [free_port() for _ in range(args.peers)],
        "peer_metrics": [free_port() for _ in range(args.peers)],
    }
    state = {"ports": ports, "pids": {}, "tls": bool(args.tls),
             "tls_ca": "", "run_dir": run_dir}
    procs = {}

    tls_server_flags, tls_client_flags = [], []
    if args.tls:
        from dragonfly2_tpu.utils.certs import CertAuthority

        ca = CertAuthority(os.path.join(run_dir, "certs"))
        cert, key = ca.cert_for("127.0.0.1")
        state["tls_ca"] = ca.ca_cert_path
        tls_server_flags = ["--tls-cert", cert, "--tls-key", key]
        tls_client_flags = ["--scheduler-tls-ca", ca.ca_cert_path]

    try:
        procs["manager"] = spawn(run_dir, "manager",
                                 "dragonfly2_tpu.cmd.manager", [
            "--host", "127.0.0.1", "--port", str(ports["manager"]),
            "--internal-port", str(ports["manager_internal"]),
            "--db", os.path.join(run_dir, "manager.db"),
            "--object-store-dir", os.path.join(run_dir, "manager-objects"),
        ])
        wait_port(ports["manager_internal"], procs["manager"], "manager")

        procs["scheduler"] = spawn(run_dir, "scheduler",
                                   "dragonfly2_tpu.cmd.scheduler", [
            "--host", "127.0.0.1", "--port", str(ports["scheduler"]),
            "--data-dir", os.path.join(run_dir, "scheduler-data"),
            "--manager", f"127.0.0.1:{ports['manager_internal']}",
            "--advertise-ip", "127.0.0.1",
            "--seed-peer", f"127.0.0.1:{ports['seed_rpc']}",
        ] + tls_server_flags)
        wait_port(ports["scheduler"], procs["scheduler"], "scheduler")

        # Daemons discover the scheduler via manager dynconfig — wait for
        # the registration + first keepalive to land so their boot-time
        # fetch already lists it.
        from dragonfly2_tpu.manager.client import ManagerHTTPClient

        mgr = ManagerHTTPClient(f"127.0.0.1:{ports['manager_internal']}")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if mgr.daemon_dynconfig(ip="127.0.0.1").get("schedulers"):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("scheduler never became active at the "
                               "manager (dynconfig lists no schedulers)")

        def daemon(name, rpc_port, metrics_port, host_type):
            p = spawn(run_dir, name, "dragonfly2_tpu.cmd.dfdaemon", [
                "--manager", f"127.0.0.1:{ports['manager_internal']}",
                "--rpc-port", str(rpc_port),
                "--metrics-port", str(metrics_port),
                "--storage-dir", os.path.join(run_dir, name),
                "--hostname", name, "--type", host_type,
                "--announce-interval", "5",
            ] + tls_client_flags)
            wait_port(rpc_port, p, name)
            return p

        procs["seed-1"] = daemon("seed-1", ports["seed_rpc"],
                                 ports["seed_metrics"], "super")
        for i in range(args.peers):
            procs[f"peer-{i}"] = daemon(
                f"peer-{i}", ports["peer_rpc"][i],
                ports["peer_metrics"][i], "normal")
    except Exception:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        raise

    state["pids"] = {name: p.pid for name, p in procs.items()}
    with open(state_path, "w") as f:
        json.dump(state, f, indent=2)
    print(json.dumps(state, indent=2))
    print(f"\nmesh up — try:\n  python -m dragonfly2_tpu.cmd.dfget "
          f"<URL> -O /tmp/out.bin --daemon "
          f"127.0.0.1:{ports['peer_rpc'][0] if args.peers else ports['seed_rpc']}")
    return 0


def cmd_down(args) -> int:
    run_dir = os.path.abspath(args.dir)
    state_path = os.path.join(run_dir, "state.json")
    with open(state_path) as f:
        state = json.load(f)
    failures = 0
    # Daemons first, control plane last (same order as service shutdown
    # in the compose file's stop_grace_period ordering).
    order = sorted(state["pids"], key=lambda n: (
        0 if n.startswith(("peer-", "seed-")) else
        1 if n == "scheduler" else 2))
    for name in order:
        pid = state["pids"][name]
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            print(f"{name} (pid {pid}): already gone")
            continue
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            time.sleep(0.1)
        else:
            print(f"{name} (pid {pid}): SIGKILL after grace", file=sys.stderr)
            os.kill(pid, signal.SIGKILL)
            failures += 1
        print(f"{name} stopped")
    os.remove(state_path)
    return 1 if failures else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("df2 local deploy")
    sub = parser.add_subparsers(dest="action", required=True)
    up = sub.add_parser("up", help="start the topology")
    up.add_argument("--dir", required=True, help="run directory")
    up.add_argument("--peers", type=int, default=2)
    up.add_argument("--tls", action="store_true",
                    help="mint a CA and TLS-terminate the scheduler wire")
    down = sub.add_parser("down", help="stop a running topology")
    down.add_argument("--dir", required=True)
    args = parser.parse_args(argv)
    return cmd_up(args) if args.action == "up" else cmd_down(args)


if __name__ == "__main__":
    sys.exit(main())
